"""Export-time pattern-fusion passes (VERDICT r4 item 5).

reference: paddle/fluid/framework/ir/{fc_fuse_pass.cc, conv_bn_fuse_pass.cc,
multihead_matmul_fuse_pass.cc} — each test asserts BOTH that the op count
shrinks and that outputs match the unfused program on the same weights.
"""

import numpy as np

import paddle_tpu as fluid
from paddle_tpu.passes import PassContext, get_pass


def _run(program, feed, fetches, scope):
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        return exe.run(program, feed=feed, fetch_list=fetches)


def _op_types(program):
    return [op.type for op in program.global_block().ops]


def test_fc_fuse(rng):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.data("x", shape=[-1, 8], dtype="float32")
        h = fluid.layers.fc(x, size=16, act="relu")
        y = fluid.layers.fc(h, size=4)
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)
    feed = {"x": rng.randn(5, 8).astype("float32")}
    before = _run(main, feed, [y.name], scope)[0]

    infer = main.clone(for_test=True)
    ctx = PassContext(scope=scope, fetch_names=[y.name])
    get_pass("fc_fuse")(infer, ctx)
    assert ctx.stats["fc_fuse"]["fused"] == 2
    types = _op_types(infer)
    assert types.count("fc") == 2
    assert "mul" not in types and "elementwise_add" not in types
    assert "relu" not in types
    after = _run(infer, feed, [y.name], scope)[0]
    np.testing.assert_allclose(before, after, rtol=1e-6, atol=1e-6)


def test_fc_fuse_skips_shared_intermediate(rng):
    """A mul output read by two consumers must NOT be folded away."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.data("x", shape=[-1, 8], dtype="float32")
        h = fluid.layers.fc(x, size=4)
        # h is also fetched -> the elementwise_add output is protected;
        # the mul output feeds only the add, but the add's out escapes
        y = fluid.layers.reduce_sum(h)
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)
    infer = main.clone(for_test=True)
    ctx = PassContext(scope=scope, fetch_names=[y.name, h.name])
    get_pass("fc_fuse")(infer, ctx)
    # the add output IS the fetch h -> fc can still fuse mul+add (writing
    # h), but must NOT swallow anything beyond it
    types = _op_types(infer)
    assert "reduce_sum" in types


def test_conv_bn_fuse(rng):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.data("img", shape=[-1, 3, 8, 8], dtype="float32")
        c = fluid.layers.conv2d(img, num_filters=6, filter_size=3, padding=1)
        b = fluid.layers.batch_norm(c)
        y = fluid.layers.reduce_sum(b)
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)
    # train steps below move the BN running stats off their init so the
    # fold has non-trivial numbers to absorb
    feed = {"img": rng.randn(4, 3, 8, 8).astype("float32")}
    for _ in range(3):
        _run(main, feed, [y.name], scope)

    infer = main.clone(for_test=True)
    before = _run(infer, feed, [y.name, b.name], scope)
    ctx = PassContext(scope=scope, fetch_names=[y.name, b.name])
    get_pass("conv_bn_fuse")(infer, ctx)
    assert ctx.stats["conv_bn_fuse"]["fused"] == 1
    types = _op_types(infer)
    assert "batch_norm" not in types
    after = _run(infer, feed, [y.name, b.name], scope)
    np.testing.assert_allclose(before[1], after[1], rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(before[0], after[0], rtol=1e-4, atol=1e-4)


def test_multihead_fuse_on_bert_attention(rng):
    """The unfused attention core of a real (tiny) BERT encoder collapses
    into scaled_dot_product_attention — the flash-served op."""
    from paddle_tpu.models import bert
    from paddle_tpu.passes import PassManager

    cfg = bert.BertConfig.tiny()  # unfused attention, dropout present
    seq = 16
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        input_ids = fluid.data("input_ids", shape=[-1, seq], dtype="int64")
        token_type = fluid.data("tt", shape=[-1, seq], dtype="int64")
        mask = fluid.data("mask", shape=[-1, seq], dtype="int64")
        seq_out, pooled = bert.bert_encoder(
            input_ids, token_type, mask, cfg, seq
        )
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)
    feed = {
        "input_ids": rng.randint(0, cfg.vocab_size, (2, seq)).astype("int64"),
        "tt": np.zeros((2, seq), "int64"),
        "mask": np.ones((2, seq), "int64"),
    }
    infer = main.clone(for_test=True)
    before = _run(infer, feed, [pooled.name], scope)[0]
    n_matmul_before = _op_types(infer).count("matmul")
    ctx = PassContext(scope=scope, fetch_names=[pooled.name])
    PassManager(["multihead_matmul_fuse"]).run(infer, ctx)
    assert ctx.stats["multihead_matmul_fuse"]["fused"] == \
        cfg.num_hidden_layers
    types = _op_types(infer)
    assert types.count("scaled_dot_product_attention") == \
        cfg.num_hidden_layers
    assert "softmax" not in types  # the attention softmaxes are gone
    assert types.count("matmul") == n_matmul_before - \
        2 * cfg.num_hidden_layers
    after = _run(infer, feed, [pooled.name], scope)[0]
    np.testing.assert_allclose(before, after, rtol=2e-4, atol=2e-5)


def test_predictor_applies_fusion_passes(rng, tmp_path):
    """End to end through the AnalysisPredictor: save a conv+bn+fc model,
    load it, and the default pass pipeline folds BN and fuses fc — same
    predictions."""
    from paddle_tpu import inference as paddle_infer

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.data("img", shape=[-1, 3, 8, 8], dtype="float32")
        c = fluid.layers.conv2d(img, num_filters=4, filter_size=3)
        bn = fluid.layers.batch_norm(c, act="relu")
        flat = fluid.layers.reshape(bn, [0, 4 * 6 * 6])
        logits = fluid.layers.fc(flat, size=3)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    feed = {"img": rng.randn(2, 3, 8, 8).astype("float32")}
    with fluid.scope_guard(scope):
        exe.run(startup)
        # reference from the TEST clone (inference BN uses moving stats,
        # not batch stats)
        ref = exe.run(
            main.clone(for_test=True), feed=feed, fetch_list=[logits.name]
        )[0]
        fluid.io.save_inference_model(
            str(tmp_path), ["img"], [logits], exe, main_program=main
        )

    config = paddle_infer.Config(str(tmp_path))
    config.disable_gpu()  # CPU test rig
    predictor = paddle_infer.create_predictor(config)
    stats = predictor._analysis_stats
    assert stats["conv_bn_fuse"]["fused"] == 1
    assert stats["fc_fuse"]["fused"] >= 1
    inp = predictor.get_input_handle(predictor.get_input_names()[0])
    inp.copy_from_cpu(feed["img"])
    predictor.run()
    out = predictor.get_output_handle(
        predictor.get_output_names()[0]
    ).copy_to_cpu()
    np.testing.assert_allclose(ref, out, rtol=1e-4, atol=1e-5)


def test_fc_fuse_skips_intermediate_read_by_while_body(rng):
    """ADVICE r5 medium regression: the fc pattern's MUL output (the
    intermediate the fusion would swallow) is also read inside a while
    body — desc-level the while op lists only its Condition input, so a
    consumer map built from op descs alone would let fc_fuse delete the
    mul whose output the loop body reads. The control-flow-aware use maps
    (analysis/usedef.py) must refuse the fusion, and the program must
    still run."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.data("x", shape=[-1, 8], dtype="float32")
        # hand-rolled fc pattern so the INTERMEDIATE (mul out) is nameable
        from paddle_tpu.layer_helper import LayerHelper

        helper = LayerHelper("fcw")
        w = helper.create_parameter(
            fluid.ParamAttr(name="fcw_w"), shape=[8, 4], dtype="float32"
        )
        b = helper.create_parameter(
            fluid.ParamAttr(name="fcw_b"), shape=[4], dtype="float32"
        )
        m = fluid.layers.mul(x, w)
        h = fluid.layers.elementwise_add(m, b)
        i = fluid.layers.fill_constant([1], "float32", 0.0)
        limit = fluid.layers.fill_constant([1], "float32", 3.0)
        s = fluid.layers.fill_constant([1], "float32", 0.0)
        cond = fluid.layers.less_than(i, limit)
        with fluid.layers.While(cond):
            t = fluid.layers.reduce_sum(m)  # sub-block read of the mul out
            ns = fluid.layers.elementwise_add(s, t)
            fluid.layers.assign(ns, s)
            ni = fluid.layers.increment(i, value=1.0, in_place=False)
            fluid.layers.assign(ni, i)
            fluid.layers.less_than(i, limit, cond=cond)
        y = fluid.layers.elementwise_add(
            fluid.layers.reduce_sum(h), s
        )
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)
    feed = {"x": rng.randn(2, 8).astype("float32")}
    before = _run(main, feed, [y.name], scope)[0]

    infer = main.clone(for_test=True)
    ctx = PassContext(scope=scope, fetch_names=[y.name])
    get_pass("fc_fuse")(infer, ctx)
    # the mul out m is consumed by the while body through its control-flow
    # op: the mul+add pair must survive un-fused
    assert ctx.stats["fc_fuse"]["fused"] == 0
    types = _op_types(infer)
    assert "mul" in types and "fc" not in types
    # and the verifier agrees the pass left the program intact
    from paddle_tpu.analysis import verify_program

    assert verify_program(infer, feed_names=["x"],
                          fetch_names=[y.name]) == []
    after = _run(infer, feed, [y.name], scope)[0]
    np.testing.assert_allclose(before, after, rtol=1e-6, atol=1e-6)
