"""Elastic gang training (r14): global-cursor data re-sharding, pinned
sync-step resume, gang-generation stamping, the ElasticGangSupervisor
shrink/grow loop, the new fault sites, and the chaos_elastic property
gate (smoke CLI + ELASTIC_EVIDENCE_r14.json drift gate in one run).
"""

import json
import os
import subprocess
import sys
import textwrap
import time

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.dataio import DataEngine, ListSource, elastic_resume
from paddle_tpu.dataio.state import IteratorState
from paddle_tpu.incubate.checkpoint import (
    AutoCheckpoint,
    CheckpointCorruptError,
    gang_generations,
    load_data_state,
)
from paddle_tpu.resilience import faults
from paddle_tpu.resilience.elastic import (
    GANG_GENERATION_ENV,
    RESUME_STEP_ENV,
    ElasticGangSupervisor,
    elastic_resume_step,
    gang_generation,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _reset_faults():
    yield
    faults.reset()


# ---------------------------------------------------------------------------
# state translation: the global sample cursor
# ---------------------------------------------------------------------------


def test_global_cursor_projection():
    st = IteratorState(epoch=2, cursor=5, base=8, world=4, rank=3)
    assert st.global_cursor() == 8 + 5 * 4
    # base survives the dict round trip (state version 2)
    st2 = IteratorState.from_dict(st.to_dict())
    assert st2.base == 8 and st2.global_cursor() == st.global_cursor()
    # version-1 blobs (no base) decode with base=0
    d = st.to_dict()
    d.pop("base")
    d["version"] = 1
    assert IteratorState.from_dict(d).base == 0


def test_elastic_resume_translation_and_validation():
    d = IteratorState(epoch=1, cursor=6, base=4, seed=7, world=4, rank=2,
                      emitted_batches=19).to_dict()
    t = IteratorState.from_dict(elastic_resume(d, 2, 1))
    assert t.base == 4 + 6 * 4 and t.cursor == 0
    assert (t.world, t.rank) == (2, 1)
    assert (t.epoch, t.seed, t.emitted_batches) == (1, 7, 19)
    with pytest.raises(ValueError):
        elastic_resume(d, 0, 0)
    with pytest.raises(ValueError):
        elastic_resume(d, 2, 2)


def test_env_constants_agree_with_checkpoint_module():
    # the literal is duplicated (import-cycle avoidance); pin equality
    from paddle_tpu.incubate import checkpoint as ck

    assert GANG_GENERATION_ENV == ck.GANG_GENERATION_ENV
    assert elastic_resume_step({RESUME_STEP_ENV: "9"}) == 9
    assert elastic_resume_step({}) is None
    assert gang_generation({GANG_GENERATION_ENV: "3"}) == 3
    assert gang_generation({}) is None


# ---------------------------------------------------------------------------
# suffix re-sharding: exactly-once tiling across arbitrary resizes
# ---------------------------------------------------------------------------


def test_epoch_shard_base_zero_is_byte_compatible():
    for world in (1, 2, 3, 5):
        for rank in range(world):
            s = ListSource(list(range(23)), seed=4, rank=rank, world=world)
            assert s.epoch_shard(1) == s.epoch_shard(1, base=0)


def test_suffix_resharding_tiles_stream_exactly():
    """Property: any schedule of (world, consumed-prefix) cuts yields
    globally contiguous positions with zero gaps/duplicates, and the
    consumed values cover the epoch order exactly once (before
    wrap-padding)."""
    import random as pyrandom

    rng = pyrandom.Random(7)
    for _ in range(100):
        n = rng.randrange(5, 50)
        seed = rng.randrange(999)
        order = ListSource(list(range(n)), seed=seed, rank=0,
                           world=1).epoch_order(0)
        consumed = []
        base = 0
        for phase in range(rng.randrange(1, 4)):
            w = rng.choice([1, 2, 3, 4])
            shards = [
                ListSource(list(range(n)), seed=seed, rank=r,
                           world=w).epoch_shard(0, base=base)
                for r in range(w)
            ]
            per = len(shards[0])
            assert all(len(s) == per for s in shards)
            if per == 0:
                break
            c = rng.randrange(0, per + 1)
            for j in range(c):
                for r in range(w):
                    consumed.append((base + j * w + r, shards[r][j]))
            base += c * w
        poss = [p for p, _ in sorted(consumed)]
        assert poss == list(range(len(poss)))
        real = [v for p, v in sorted(consumed) if p < n]
        assert real == order[:len(real)]


def test_engine_elastic_resume_translates_and_strict_mode_still_rejects():
    src4 = ListSource(list(range(32)), seed=5, rank=0, world=4)
    e4 = DataEngine(src4, batch_size=2, drop_last=True)
    it = iter(e4)
    next(it), next(it)
    st = e4.state_dict()

    # strict engine (default): world mismatch still raises
    strict = DataEngine(ListSource(list(range(32)), seed=5, rank=0,
                                   world=2), batch_size=2, drop_last=True)
    with pytest.raises(Exception):
        strict.load_state_dict(st)

    # elastic engine: translates to the global cursor
    el = DataEngine(ListSource(list(range(32)), seed=5, rank=1, world=2),
                    batch_size=2, drop_last=True, elastic=True)
    el.load_state_dict(st)
    assert el.base == st["base"] + st["cursor"] * st["world"]
    assert el.cursor == 0 and el.epoch == st["epoch"]
    # same-geometry load through an elastic engine stays a plain resume
    el2 = DataEngine(ListSource(list(range(32)), seed=5, rank=0, world=4),
                     batch_size=2, drop_last=True, elastic=True)
    el2.load_state_dict(st)
    assert el2.cursor == st["cursor"] and el2.base == st["base"]


def test_engine_schedule_stream_is_replay_deterministic():
    """The engine-level half of the chaos property: driving fresh
    engines through the same (world, steps) schedule twice yields the
    identical stream, and positions tile each epoch exactly."""

    def run(schedule, n=24, seed=3, bs=2):
        state, stream = None, []
        for w, steps in schedule:
            engines, iters = [], []
            for r in range(w):
                e = DataEngine(ListSource(list(range(n)), seed=seed,
                                          rank=r, world=w),
                               batch_size=bs, drop_last=True, elastic=True)
                if state is not None:
                    e.load_state_dict(state)
                engines.append(e)
                iters.append(iter(e))
            for _ in range(steps):
                for r in range(w):
                    e = engines[r]
                    try:
                        b = next(iters[r])
                    except StopIteration:
                        iters[r] = iter(e)
                        b = next(iters[r])
                    p0 = e.base + (e.cursor - bs) * w + r
                    for k, v in enumerate(b):
                        stream.append((e.epoch, p0 + k * w, v))
            state = engines[0].state_dict()
        return stream

    sched = [(2, 3), (3, 1), (4, 2), (1, 4)]
    s1, s2 = run(sched), run(sched)
    assert s1 == s2
    by_epoch = {}
    for ep, p, v in s1:
        by_epoch.setdefault(ep, []).append(p)
    for ep, poss in by_epoch.items():
        assert sorted(poss) == list(range(len(poss))), ep


def test_prefetcher_global_cursor_is_consumer_exact():
    from paddle_tpu.dataio import DevicePrefetcher

    src = ListSource(list(range(16)), seed=2, rank=0, world=2)
    eng = DataEngine(src, batch_size=2, drop_last=True)
    pre = DevicePrefetcher(eng, depth=2)
    it = iter(pre)
    next(it)
    time.sleep(0.2)  # let the producer read ahead
    # consumer has seen ONE batch of 2 samples at world 2
    assert pre.global_cursor() == 2 * 2
    assert eng.global_cursor >= pre.global_cursor()


# ---------------------------------------------------------------------------
# checkpoint: pinned sync-step resume + gang-generation stamps
# ---------------------------------------------------------------------------


def _train_ckpt(tmp_path, steps, interval=2, gen_env=None, dirname="ck"):
    from paddle_tpu.core.ir import Program, program_guard

    if gen_env is not None:
        os.environ[GANG_GENERATION_ENV] = str(gen_env)
    try:
        main, startup = Program(), Program()
        with program_guard(main, startup):
            x = fluid.data("x", shape=[-1, 4])
            pred = fluid.layers.fc(x, size=1)
            loss = fluid.layers.mean(pred)
            fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        feed = {"x": np.ones((4, 4), dtype=np.float32)}
        with fluid.scope_guard(scope):
            exe.run(startup)
            ck = AutoCheckpoint(exe, main, str(tmp_path / dirname),
                                save_interval_steps=interval, scope=scope,
                                max_to_keep=16)
            start = ck.resume()
            for step in range(start, steps):
                exe.run(main, feed=feed, fetch_list=[loss])
                ck.maybe_save(step, blocking=True)
            ck.close()
        return str(tmp_path / dirname)
    finally:
        if gen_env is not None:
            del os.environ[GANG_GENERATION_ENV]


def test_pinned_step_resume_and_strictness(tmp_path):
    d = _train_ckpt(tmp_path, steps=8, interval=2)  # saves at 1,3,5,7
    from paddle_tpu.incubate.checkpoint import load_checkpoint

    scope = fluid.Scope()
    assert load_checkpoint(d, scope=scope, step=3) == 4
    # pinned step that never existed: loud, no silent walk-back
    with pytest.raises(CheckpointCorruptError):
        load_checkpoint(d, scope=fluid.Scope(), step=4)
    # pinned step corrupted: quarantined + loud
    from paddle_tpu.resilience import corrupt_file

    corrupt_file(os.path.join(d, "ckpt_5", "state.npz"))
    with pytest.raises(CheckpointCorruptError):
        load_checkpoint(d, scope=fluid.Scope(), step=5)
    assert any(".corrupt" in n for n in os.listdir(d))
    # un-pinned resume still walks back past the quarantined entry
    assert load_checkpoint(d, scope=fluid.Scope()) == 8


def test_gang_generation_stamped_and_monotone(tmp_path):
    d = _train_ckpt(tmp_path, steps=4, interval=2, gen_env=0)
    _train_ckpt(tmp_path, steps=8, interval=2, gen_env=1)
    chain = gang_generations(d)
    steps = [s for s, _ in chain]
    gens = [g for _, g in chain]
    assert steps == sorted(steps) and gens == [0, 0, 1, 1]
    # meta.json carries it too
    with open(os.path.join(d, "ckpt_7", "meta.json")) as f:
        assert json.load(f)["gang_generation"] == 1
    # unstamped checkpoints read back as None
    d2 = _train_ckpt(tmp_path, steps=2, interval=2, dirname="ck2")
    assert gang_generations(d2) == [(1, None)]


def test_load_data_state_reads_blob_without_scope(tmp_path):
    from paddle_tpu.core.ir import Program, program_guard

    src = ListSource(list(range(16)), seed=1, rank=0, world=4)
    eng = DataEngine(src, batch_size=2, drop_last=True)
    it = iter(eng)
    next(it)
    main, startup = Program(), Program()
    with program_guard(main, startup):
        x = fluid.data("x", shape=[-1, 2])
        fluid.layers.fc(x, size=1)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        ck = AutoCheckpoint(exe, main, str(tmp_path / "ck"),
                            save_interval_steps=1, scope=scope,
                            data_state=eng)
        ck.save(0, blocking=True)
    blob = load_data_state(str(tmp_path / "ck"), step=0)
    assert blob["world"] == 4 and blob["cursor"] == 2
    assert load_data_state(str(tmp_path / "ck")) == blob
    # a corrupt pinned entry is quarantined AND loud (same contract as
    # load_checkpoint's pinned branch)
    from paddle_tpu.resilience import corrupt_file

    corrupt_file(os.path.join(str(tmp_path / "ck"), "ckpt_0",
                              "state.npz"))
    with pytest.raises(CheckpointCorruptError):
        load_data_state(str(tmp_path / "ck"), step=0)
    assert any(".corrupt" in n for n in os.listdir(tmp_path / "ck"))


# ---------------------------------------------------------------------------
# fault sites: worker.preempt (term) + elastic.resize
# ---------------------------------------------------------------------------


def test_term_action_parses_and_sigterms_subprocess(tmp_path):
    # schedule validation accepts the new action (and still rejects junk)
    faults.configure([{"site": "worker.preempt", "action": "term"}])
    faults.reset()
    with pytest.raises(ValueError):
        faults.configure([{"site": "x", "action": "vaporize"}])
    # a subprocess firing the site dies with -SIGTERM (not the hard-kill
    # exit code): the preemption shape, catchable in principle
    code = textwrap.dedent("""
        import os, sys
        sys.path.insert(0, %r)
        from paddle_tpu.resilience import faults
        faults.configure([{"site": "worker.preempt", "action": "term",
                           "at_step": 2}])
        for step in range(5):
            faults.fire("worker.preempt", step=step)
        print("SURVIVED")
    """ % REPO)
    proc = subprocess.run([sys.executable, "-c", code],
                          capture_output=True, text=True, timeout=120)
    assert proc.returncode == -15, (proc.returncode, proc.stdout)
    assert "SURVIVED" not in proc.stdout


def _trivial_worker(tmp_path, body):
    path = tmp_path / "w.py"
    path.write_text(textwrap.dedent(body))
    return str(path)


def test_elastic_resize_fault_degrades_to_same_size_restart(tmp_path):
    """An injected failure at the elastic.resize site falls back to the
    classic same-size restart instead of resizing — the resize path is
    itself a hardened path."""
    script = _trivial_worker(tmp_path, """
        import os, sys
        if (os.environ["PADDLE_ELASTIC_GANG_GENERATION"] == "0"
                and os.environ["PADDLE_TRAINER_ID"] == "1"):
            sys.exit(9)
        sys.exit(0)
    """)
    faults.configure([{"site": "elastic.resize", "action": "raise"}])
    try:
        sup = ElasticGangSupervisor([script], nproc=2, min_nproc=1,
                                    capacity_fn=lambda: 1,
                                    max_restarts=2, restart_backoff_s=0.05)
        codes = sup.run()
    finally:
        faults.reset()
    assert codes == [0, 0]
    kinds = [e["kind"] for e in sup.events]
    assert "resize_fault" in kinds
    assert "gang_resize" not in kinds         # the resize was degraded
    assert sup.world == 2                     # same-size restart
    assert sup.generation == 1                # but a new generation


# ---------------------------------------------------------------------------
# ElasticGangSupervisor policy
# ---------------------------------------------------------------------------


def test_supervisor_shrinks_on_loss_and_grows_on_capacity(tmp_path):
    script = _trivial_worker(tmp_path, """
        import os, sys, time
        gen = int(os.environ["PADDLE_ELASTIC_GANG_GENERATION"])
        rank = int(os.environ["PADDLE_TRAINER_ID"])
        world = int(os.environ["PADDLE_TRAINERS_NUM"])
        if gen == 0:
            assert world == 4, world
            if rank == 3:
                sys.exit(7)
        time.sleep(1.0)
        sys.exit(0)
    """)
    state = {"phase": 0}

    def capacity():
        return 2 if state["phase"] == 0 else 4

    sup = ElasticGangSupervisor([script], nproc=4, min_nproc=2,
                                capacity_fn=capacity, capacity_poll_s=0.2,
                                max_restarts=3, restart_backoff_s=0.05)
    orig = sup._decide_world

    def decide(failure):
        w = orig(failure)
        if failure["kind"] == "rank_exit":
            state["phase"] = 1   # capacity returns once the gang shrank
        return w

    sup._decide_world = decide
    codes = sup.run()
    assert codes == [0, 0, 0, 0]
    assert (4, 2, 1) in sup.resizes and (2, 4, 2) in sup.resizes
    assert sup.restarts == 1          # the grow never charged the budget
    gauge = None
    from paddle_tpu.observability import registry

    gauge = registry().gauge("elastic_world_size",
                             "current world size of the elastic "
                             "training gang")
    assert gauge.value == 4
    hist = registry().histogram(
        "elastic_resize_seconds",
        "failure/capacity detection to resized-gang spawn")
    assert hist.count >= 2


def test_supervisor_never_goes_below_min_nproc(tmp_path):
    script = _trivial_worker(tmp_path, """
        import os, sys
        if os.environ["PADDLE_ELASTIC_GANG_GENERATION"] in ("0", "1"):
            sys.exit(5)
        sys.exit(0)
    """)
    sup = ElasticGangSupervisor([script], nproc=3, min_nproc=2,
                                capacity_fn=lambda: 1,   # wants 1: clamped
                                max_restarts=3, restart_backoff_s=0.05)
    codes = sup.run()
    assert codes == [0, 0]
    worlds = [e["new_world"] for e in sup.events
              if e["kind"] == "gang_resize"]
    assert worlds and all(w >= 2 for w in worlds)
    assert sup.world == 2


def test_sync_step_is_newest_common_valid_entry(tmp_path):
    """Fabricated per-rank chains: the sync step must be the newest step
    EVERY active rank holds, skipping corrupt candidates (quarantined)."""
    import io as _io
    import zlib

    def fake_ckpt(d, step, corrupt=False):
        os.makedirs(os.path.join(d, f"ckpt_{step}"), exist_ok=True)
        p = os.path.join(d, f"ckpt_{step}")
        arr = np.arange(4, dtype=np.float32)
        buf = _io.BytesIO()
        np.savez(buf, w=arr)
        raw = buf.getvalue()
        with open(os.path.join(p, "state.npz"), "wb") as f:
            f.write(raw)
        manifest = {"format": 1, "step": step, "arrays": {},
                    "files": {"state.npz": {
                        "size": len(raw) + (7 if corrupt else 0),
                        "crc32": zlib.crc32(raw) & 0xFFFFFFFF}}}
        with open(os.path.join(p, "manifest.json"), "w") as f:
            json.dump(manifest, f)
        with open(os.path.join(p, "meta.json"), "w") as f:
            json.dump({"step": step}, f)

    dirs = [str(tmp_path / f"rank{r}") for r in range(3)]
    for r, d in enumerate(dirs):
        for s in (1, 3, 5):
            fake_ckpt(d, s)
    fake_ckpt(dirs[1], 7)               # rank1 ran ahead: not common
    fake_ckpt(dirs[2], 5, corrupt=True)  # rank2's newest common is torn

    sup = ElasticGangSupervisor(["x.py"], nproc=3, min_nproc=1,
                                checkpoint_dirs=dirs)
    assert sup._sync_step() == 3
    # the torn candidate was quarantined on the walk
    assert any(".corrupt" in n for n in os.listdir(dirs[2]))
    # no checkpoints at all -> fresh start
    sup2 = ElasticGangSupervisor(["x.py"], nproc=2, min_nproc=1,
                                 checkpoint_dirs=[str(tmp_path / "empty0"),
                                                  str(tmp_path / "empty1")])
    assert sup2._sync_step() is None


def test_launch_cli_elastic_flags(tmp_path):
    """--min_nproc/--elastic route through ElasticGangSupervisor; the
    classic path stays untouched without them."""
    script = _trivial_worker(tmp_path, """
        import os, sys
        assert "PADDLE_ELASTIC_GANG_GENERATION" in os.environ
        sys.exit(0)
    """)
    proc = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc", "2", "--min_nproc", "1", script],
        capture_output=True, text=True, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu",
             "PYTHONPATH": REPO + os.pathsep + os.environ.get(
                 "PYTHONPATH", "")},
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    classic = _trivial_worker(tmp_path, """
        import os, sys
        assert "PADDLE_ELASTIC_GANG_GENERATION" not in os.environ
        sys.exit(0)
    """)
    os.replace(str(tmp_path / "w.py"), str(tmp_path / "w2.py"))
    proc = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc", "2", str(tmp_path / "w2.py")],
        capture_output=True, text=True, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu",
             "PYTHONPATH": REPO + os.pathsep + os.environ.get(
                 "PYTHONPATH", "")},
    )
    assert proc.returncode == 0, proc.stderr[-2000:]


# ---------------------------------------------------------------------------
# the property gate: chaos smoke CLI + evidence drift gate (ONE run)
# ---------------------------------------------------------------------------


def test_elastic_evidence_r14_committed(tmp_path):
    """Runs `tools/chaos_elastic.py --smoke --evidence` LIVE (kill a
    rank mid-step -> shrink 4->2 -> grow 2->4, replay-determinism +
    exactly-once + monotone generations asserted inside the CLI) and
    drift-gates the committed ELASTIC_EVIDENCE_r14.json against the
    recompute: committed claims must re-derive byte-for-byte."""
    out = tmp_path / "ev.json"
    env = dict(os.environ)
    env.pop("PADDLE_TPU_FAULTS", None)
    env.pop("PADDLE_TPU_FAULT_STATE", None)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "chaos_elastic.py"),
         "--smoke", "--evidence", str(out)],
        capture_output=True, text=True, timeout=540, env=env,
    )
    assert proc.returncode == 0, \
        proc.stdout[-4000:] + proc.stderr[-4000:]
    assert "CHAOS_ELASTIC_OK" in proc.stdout
    with open(out) as f:
        live = json.load(f)
    with open(os.path.join(REPO, "ELASTIC_EVIDENCE_r14.json")) as f:
        committed = json.load(f)
    assert committed["scenario"] == live["scenario"], (
        "scenario drift: regenerate ELASTIC_EVIDENCE_r14.json")
    assert committed["invariants"] == live["invariants"], {
        k: (committed["invariants"].get(k), live["invariants"].get(k))
        for k in set(committed["invariants"]) | set(live["invariants"])
        if committed["invariants"].get(k) != live["invariants"].get(k)
    }
    inv = live["invariants"]
    assert inv["bit_identical"] and inv["lost_or_duplicated"] == 0
    assert inv["generations"] == [0, 1, 2]
