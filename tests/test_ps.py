"""Parameter-server stack tests.

Reference patterns: operators/distributed rpc_server_test.cc (loopback
server), test_dist_fleet_base.py (PS fleet training), dist_ctr.py (CTR
model). The native server runs in-process on a loopback port."""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.distributed.ps import (
    OPT_ADAGRAD,
    Communicator,
    PSClient,
    PSServer,
)


@pytest.fixture
def ps():
    srv = PSServer()
    client = PSClient([srv.endpoint])
    yield srv, client
    client.close()
    srv.stop()


def test_pull_push_sparse(ps):
    _, c = ps
    c.create_table(1, dim=4, init_range=0.05)
    ids = np.array([10, 20, 10], dtype=np.uint64)
    rows = c.pull_sparse(1, ids, 4)
    assert rows.shape == (3, 4)
    np.testing.assert_array_equal(rows[0], rows[2])
    assert (np.abs(rows) <= 0.05).all()
    c.push_sparse(1, np.array([10], dtype=np.uint64),
                  np.full((1, 4), 2.0, np.float32), lr=0.25)
    after = c.pull_sparse(1, np.array([10], dtype=np.uint64), 4)
    np.testing.assert_allclose(after[0], rows[0] - 0.5, rtol=1e-6)


def test_adagrad_table(ps):
    _, c = ps
    c.create_table(3, dim=2, init_range=0.0, optimizer=OPT_ADAGRAD)
    ids = np.array([7], dtype=np.uint64)
    g = np.array([[3.0, 4.0]], dtype=np.float32)
    c.push_sparse(3, ids, g, lr=0.1)
    got = c.pull_sparse(3, ids, 2)
    # adagrad: w -= lr * g / (sqrt(g^2) + eps) = -lr * sign(g)
    np.testing.assert_allclose(got[0], [-0.1, -0.1], atol=1e-5)


def test_dense_table_and_checkpoint(ps, tmp_path):
    _, c = ps
    c.create_table(2, dense_size=8, is_dense=True)
    c.push_dense(2, np.arange(8, dtype=np.float32), lr=1.0)
    np.testing.assert_allclose(c.pull_dense(2), -np.arange(8))
    path = str(tmp_path / "dense.tbl")
    c.save(2, path)
    c.push_dense(2, np.ones(8, dtype=np.float32), lr=1.0)
    c.load(2, path)
    np.testing.assert_allclose(c.pull_dense(2), -np.arange(8))


def test_shrink_and_stats(ps):
    _, c = ps
    c.create_table(4, dim=2)
    for step in range(5):
        c.push_sparse(4, np.array([step], dtype=np.uint64),
                      np.ones((1, 2), np.float32), lr=0.1)
    assert c.table_stats()[4] == 5
    dropped = c.shrink(4, keep_versions=2)
    assert dropped == 3
    assert c.table_stats()[4] == 2


def test_stop_with_open_connection_does_not_hang():
    """Server stop must shutdown() connections parked in recv()."""
    import threading

    srv = PSServer()
    c = PSClient([srv.endpoint])  # idle connection, blocked server-side
    done = threading.Event()

    def stopper():
        srv.stop()
        done.set()

    t = threading.Thread(target=stopper, daemon=True)
    t.start()
    assert done.wait(timeout=10), "PSServer.stop() hung with an open client"
    c.close()


def test_shrink_after_load_keeps_rows(ps, tmp_path):
    """Loaded rows join the current version generation — a shrink right
    after checkpoint restore must not wipe the table."""
    _, c = ps
    c.create_table(6, dim=2, init_range=0.0)
    for i in range(3):
        c.push_sparse(6, np.array([i], dtype=np.uint64),
                      np.ones((1, 2), np.float32), lr=0.1)
    path = str(tmp_path / "t6.tbl")
    c.save(6, path)
    c.load(6, path)
    assert c.shrink(6, keep_versions=1000) == 0
    assert c.table_stats()[6] == 3


def test_adagrad_state_survives_checkpoint(ps, tmp_path):
    """g2 accumulators are part of the checkpoint: post-restore updates must
    be damped exactly as pre-restore ones."""
    _, c = ps
    c.create_table(7, dim=1, init_range=0.0, optimizer=OPT_ADAGRAD)
    ids = np.array([1], dtype=np.uint64)
    g = np.array([[2.0]], dtype=np.float32)
    c.push_sparse(7, ids, g, lr=0.1)
    path = str(tmp_path / "t7.tbl")
    c.save(7, path)
    c.push_sparse(7, ids, g, lr=0.1)
    expected = c.pull_sparse(7, ids, 1).copy()
    c.load(7, path)
    c.push_sparse(7, ids, g, lr=0.1)  # must replay identically
    np.testing.assert_allclose(c.pull_sparse(7, ids, 1), expected, rtol=1e-6)


def test_heartbeat(ps):
    _, c = ps
    ages = c.heartbeat(3)
    assert 3 in ages and ages[3] < 1.0


def test_multi_server_sharding():
    srvs = [PSServer(), PSServer()]
    c = PSClient([s.endpoint for s in srvs])
    try:
        c.create_table(1, dim=4, init_range=0.1)
        ids = np.arange(100, dtype=np.uint64)
        rows = c.pull_sparse(1, ids, 4)
        assert rows.shape == (100, 4)
        # routing is stable: re-pull matches
        np.testing.assert_array_equal(rows, c.pull_sparse(1, ids, 4))
        # each server holds only its residue class
        stats = c.table_stats()
        assert stats[1] == 100
    finally:
        c.close()
        for s in srvs:
            s.stop()


def test_communicator_merges_duplicates(ps):
    _, c = ps
    c.create_table(5, dim=2, init_range=0.0)
    comm = Communicator(c, mode="async", merge_steps=8)
    for _ in range(4):
        comm.push_sparse(5, np.array([1, 1], dtype=np.uint64),
                         np.ones((2, 2), np.float32), 0.1)
    comm.stop()
    got = c.pull_sparse(5, np.array([1], dtype=np.uint64), 2)
    # 4 pushes x 2 duplicate rows x grad 1.0 x lr 0.1 = -0.8
    np.testing.assert_allclose(got[0], [-0.8, -0.8], atol=1e-5)


# ---------------------------------------------------------------------------
# end-to-end CTR training through the PS fleet
# ---------------------------------------------------------------------------


def test_ctr_ps_training_converges(rng):
    from paddle_tpu.fleet import parameter_server as psfleet
    from paddle_tpu.fleet.role_maker import UserDefinedRoleMaker
    from paddle_tpu.models import ctr

    fleet = psfleet.fleet
    fleet.init(UserDefinedRoleMaker(current_id=0, worker_num=1))
    main, startup, feeds, fetches = ctr.build_ctr_train(
        num_slots=4, ids_per_slot=2, deep_dim=8, hidden=(16,), sparse_lr=0.2
    )
    srv = fleet.init_server(port=0)
    try:
        fleet.init_worker(main)
        exe = fluid.Executor(fluid.CPUPlace())
        with fluid.scope_guard(fluid.Scope()):
            exe.run(startup)
            worker = fleet.worker(exe, main)
            losses = []
            feed = ctr.synthetic_batch(rng, 64, num_slots=4, ids_per_slot=2)
            for _ in range(30):
                out = worker.run(main, feed, fetch_list=[fetches[0]])
                losses.append(float(out[0][0]))
            worker.flush()
        assert np.isfinite(losses).all()
        assert losses[-1] < losses[0], (losses[0], losses[-1])
        # sparse rows actually moved server-side
        stats = fleet._client.table_stats()
        assert sum(stats.values()) > 0
    finally:
        fleet.stop_worker()
        srv.stop()


def test_ctr_ps_matches_local_embedding(rng):
    """Loss parity: PS-backed sparse embedding vs on-device dense embedding
    with identical (zero) init and SGD lr must produce the same loss curve
    (the reference's TestDistBase methodology, test_dist_base.py:506)."""
    from paddle_tpu.fleet import parameter_server as psfleet
    from paddle_tpu.fleet.role_maker import UserDefinedRoleMaker
    from paddle_tpu.models import ctr

    vocab = 50
    lr = 0.3

    def small_batch():
        r = np.random.RandomState(42)
        feeds = []
        for _ in range(6):
            feed = {}
            for i in range(2):
                feed[f"slot_{i}"] = r.randint(
                    0, vocab, size=(16, 2)).astype("int64")
            feed["click"] = (r.rand(16, 1) > 0.5).astype("float32")
            feeds.append(feed)
        return feeds

    # local baseline: dense embedding tables, zero-init, plain SGD
    main_l, startup_l, _, fetches_l = ctr.build_ctr_train(
        num_slots=2, ids_per_slot=2, deep_dim=4, hidden=(8,),
        optimizer=fluid.optimizer.SGD(learning_rate=lr),
        ps_mode=False, vocab_size=vocab,
    )
    # zero-init ALL embedding tables for parity with init_range=0 PS rows
    with fluid.program_guard(main_l, startup_l):
        pass
    exe = fluid.Executor(fluid.CPUPlace())
    ref_losses = []
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup_l)
        # overwrite deep tables with zeros for exact parity
        scope = fluid.global_scope()
        for v in main_l.all_parameters():
            if v.name.startswith("deep_") and v.name.endswith("_w"):
                scope.set(v.name, np.zeros(v.shape, dtype=np.float32))
        for feed in small_batch():
            out = exe.run(main_l, feed=feed, fetch_list=[fetches_l[0]])
            ref_losses.append(float(out[0][0]))

    # PS run: init_range=0 -> zero rows; sync mode; same sparse lr
    fleet = psfleet.fleet
    fleet.init(UserDefinedRoleMaker(current_id=0, worker_num=1))
    main_p, startup_p, _, fetches_p = ctr.build_ctr_train(
        num_slots=2, ids_per_slot=2, deep_dim=4, hidden=(8,),
        optimizer=fluid.optimizer.SGD(learning_rate=lr),
        sparse_lr=lr, ps_mode=True,
    )
    # zero the deep-embedding init range for parity
    for t in main_p._sparse_tables.values():
        t["init_range"] = 0.0
    srv = fleet.init_server(port=0)
    ps_losses = []
    try:
        fleet.init_worker(main_p)
        with fluid.scope_guard(fluid.Scope()):
            exe.run(startup_p)
            worker = fleet.worker(exe, main_p)
            for feed in small_batch():
                out = worker.run(main_p, feed, fetch_list=[fetches_p[0]])
                ps_losses.append(float(out[0][0]))
            worker.flush()
    finally:
        fleet.stop_worker()
        srv.stop()

    # dense (fc) params share init across builds (same seeds/order), sparse
    # tables are zero in both: trajectories must match closely
    np.testing.assert_allclose(ref_losses, ps_losses, rtol=2e-3, atol=2e-4)


def test_geo_sgd_delta_sync(rng):
    """GEO mode: dense params train locally, deltas merge via the server
    every merge_steps (reference: python/paddle/fluid/transpiler/
    geo_sgd_transpiler.py). Single worker: after each sync the server's
    global copy equals the worker's params; training still converges."""
    from paddle_tpu.fleet import parameter_server as psfleet
    from paddle_tpu.fleet.role_maker import UserDefinedRoleMaker
    from paddle_tpu.models import ctr

    fleet = psfleet.fleet
    fleet.init(UserDefinedRoleMaker(current_id=0, worker_num=1))
    main, startup, feeds, fetches = ctr.build_ctr_train(
        num_slots=4, ids_per_slot=2, deep_dim=8, hidden=(16,), sparse_lr=0.2
    )
    strategy = psfleet.PSDistributedStrategy(mode="geo", merge_steps=3)
    srv = fleet.init_server(port=0)
    try:
        fleet.init_worker(main)
        fleet._strategy = strategy
        exe = fluid.Executor(fluid.CPUPlace())
        with fluid.scope_guard(fluid.Scope()):
            exe.run(startup)
            worker = fleet.worker(exe, main)
            assert worker._geo and worker._geo_params
            losses = []
            feed = ctr.synthetic_batch(rng, 64, num_slots=4, ids_per_slot=2)
            for _ in range(10):  # deliberately NOT a multiple of merge_steps
                out = worker.run(main, feed, fetch_list=[fetches[0]])
                losses.append(float(out[0][0]))
            worker.flush()  # ships the partial window tail (step 10)
            # after flush the global dense copy matches the local params
            merged = fleet._client.pull_dense(psfleet.PSWorker.GEO_DENSE_TABLE)
            np.testing.assert_allclose(
                merged, worker._concat_params(), rtol=1e-5, atol=1e-6
            )
        assert losses[-1] < losses[0], (losses[0], losses[-1])
    finally:
        fleet.stop_worker()
        srv.stop()


# ---------------------------------------------------------------------------
# in-graph remote lookup (distributed_embedding -> io_callback pull/push)
# ---------------------------------------------------------------------------


def _remote_ctr_batches(vocab=50, n=6):
    r = np.random.RandomState(42)
    feeds = []
    for _ in range(n):
        feed = {}
        for i in range(2):
            feed[f"slot_{i}"] = r.randint(
                0, vocab, size=(16, 2)).astype("int64")
        feed["click"] = (r.rand(16, 1) > 0.5).astype("float32")
        feeds.append(feed)
    return feeds


def test_remote_lookup_in_graph_parity_and_prefetch():
    """The table exists ONLY on the servers; the pull happens INSIDE the
    compiled step (io_callback, reference: distributed/
    parameter_prefetch.cc:1) and the backward pushes merged row grads.
    Loss curve must match a local dense-embedding run with identical
    (zero) init and the same SGD lr; announced next-batch ids must be
    served from the prefetch buffer, not a blocking pull."""
    from paddle_tpu.distributed import lookup as rl
    from paddle_tpu.fleet import parameter_server as psfleet
    from paddle_tpu.fleet.role_maker import UserDefinedRoleMaker
    from paddle_tpu.models import ctr

    vocab, lr = 50, 0.3

    # local baseline: dense tables, zero-init, one SGD rule for everything
    main_l, startup_l, _, fetches_l = ctr.build_ctr_train(
        num_slots=2, ids_per_slot=2, deep_dim=4, hidden=(8,),
        optimizer=fluid.optimizer.SGD(learning_rate=lr),
        ps_mode=False, vocab_size=vocab,
    )
    # one executor PER ARM: the rng counter advances per run() call, so a
    # shared executor would give the two startup programs different keys
    # and thus different fc inits (step-0 loss is ln 2 regardless — zero
    # embeddings zero the logits — so that difference only shows later)
    exe = fluid.Executor(fluid.CPUPlace())
    ref_losses = []
    dense_init = {}
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup_l)
        scope = fluid.global_scope()
        for v in main_l.all_parameters():
            if v.name.startswith("deep_") and v.name.endswith("_w"):
                scope.set(v.name, np.zeros(v.shape, dtype=np.float32))
            elif not v.name.startswith("wide_"):
                # snapshot dense (fc) inits IN CREATION ORDER: the two arms'
                # startup programs differ in op count (different rng
                # streams) and in name-counter state (different var names),
                # so parity seeds the remote arm positionally with THESE
                dense_init[v.name] = np.asarray(scope.find_var(v.name)).copy()
        for feed in _remote_ctr_batches(vocab):
            out = exe.run(main_l, feed=feed, fetch_list=[fetches_l[0]])
            ref_losses.append(float(out[0][0]))

    fleet = psfleet.fleet
    fleet.init(UserDefinedRoleMaker(current_id=0, worker_num=1))
    main_r, startup_r, _, fetches_r = ctr.build_ctr_train(
        num_slots=2, ids_per_slot=2, deep_dim=4, hidden=(8,),
        optimizer=fluid.optimizer.SGD(learning_rate=lr),
        sparse_lr=lr, ps_mode="remote",
    )
    assert main_r._remote_tables and not getattr(
        main_r, "_sparse_tables", {}
    ), "remote mode must register only in-graph tables"
    push_ops = [
        op for op in main_r.global_block().ops
        if op.type == "distributed_push_sparse"
    ]
    assert len(push_ops) == len(main_r._remote_tables)
    srv = fleet.init_server(port=0)
    remote_losses = []
    try:
        fleet.init_worker(main_r)
        ctx = rl.active_context()
        assert ctx is not None
        batches = _remote_ctr_batches(vocab)
        exe = fluid.Executor(fluid.CPUPlace())
        with fluid.scope_guard(fluid.Scope()):
            exe.run(startup_r)
            scope = fluid.global_scope()
            remote_dense = [
                v for v in main_r.all_parameters()
                if not v.name.startswith(("wide_", "deep_"))
            ]
            assert len(remote_dense) == len(dense_init)
            for v, val in zip(remote_dense, dense_init.values()):
                assert tuple(v.shape) == val.shape, (v.name, v.shape)
                scope.set(v.name, val)
            # plain exe.run: NO host-side feed rewrite — pulls and pushes
            # ride the step's io_callbacks
            for step, feed in enumerate(batches):
                if step + 1 < len(batches):
                    # double-buffer: announce next batch's ids now; the
                    # pull is fenced behind this step's pushes so the
                    # prefetched rows are not one-update stale
                    rl.prefetch_for_program(main_r, batches[step + 1])
                out = exe.run(main_r, feed=feed, fetch_list=[fetches_r[0]])
                remote_losses.append(float(out[0][0]))
        # rows live server-side only
        stats = fleet._client.table_stats()
        assert sum(stats.values()) > 0
        assert ctx.stats["pushes"] > 0
        # steps 2..N pulled every table from the prefetch buffer
        n_tables = len(main_r._remote_tables)
        assert ctx.stats["prefetch_hits"] >= (len(batches) - 1) * n_tables
        # step 1 had no announcement: sync pulls only there
        assert ctx.stats["pulls"] <= n_tables
    finally:
        fleet.stop_worker()
        srv.stop()
    np.testing.assert_allclose(ref_losses, remote_losses, rtol=1e-5, atol=1e-6)


def test_remote_lookup_without_context_raises():
    """A ported PS program must fail loudly outside the fleet, not silently
    train on a local dense table (VERDICT r4 weak item 3)."""
    from paddle_tpu.models import ctr
    from paddle_tpu.utils.enforce import EnforceError

    main, startup, _, fetches = ctr.build_ctr_train(
        num_slots=2, ids_per_slot=2, deep_dim=4, hidden=(8,),
        optimizer=fluid.optimizer.SGD(learning_rate=0.1),
        ps_mode="remote",
    )
    exe = fluid.Executor(fluid.CPUPlace())
    feed = _remote_ctr_batches()[0]
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        with pytest.raises(EnforceError, match="remote|context"):
            exe.run(main, feed=feed, fetch_list=[fetches[0]])


# ---------------------------------------------------------------------------
# Downpour dataset-mode e2e: data_generator files -> train_from_dataset
# (DownpourSGD device worker) -> global AUC via FleetUtil
# ---------------------------------------------------------------------------


def test_downpour_dataset_mode_e2e(tmp_path):
    """The reference's dataset-mode PS path as ONE wired flow
    (reference: python/paddle/fluid/device_worker.py:95 DownpourSGD,
    trainer_desc.py:236 DistMultiTrainer): MultiSlot files written by a
    data generator feed an InMemoryDataset; train_from_dataset reads the
    program's _fleet_opt, builds the DistMultiTrainer + DownpourSGD worker
    via TrainerFactory, and drives pull -> step -> push per batch against
    the native PS; FleetUtil reads the trained global AUC from the auc
    op's accumulators."""
    from paddle_tpu.fleet import parameter_server as psfleet
    from paddle_tpu.fleet.role_maker import UserDefinedRoleMaker
    from paddle_tpu.incubate.data_generator import MultiSlotDataGenerator
    from paddle_tpu.incubate.fleet_utils import FleetUtil

    # 1. data files from the generator: id slot + clicky label (click
    #    correlates with id parity so there is signal to learn)
    class G(MultiSlotDataGenerator):
        def generate_sample(self, line):
            def it():
                toks = [int(x) for x in line.split()]
                yield [("ids", toks), ("click", [1 if toks[0] % 3 else 0])]

            return it

    r = np.random.RandomState(7)
    lines = [f"{r.randint(0, 40)} {r.randint(0, 40)}" for _ in range(256)]
    out_lines = G().run_from_memory(lines)
    data_file = tmp_path / "part-0"
    data_file.write_text("\n".join(out_lines) + "\n")

    # 2. CTR program on PS sparse embeddings + in-graph AUC
    fleet = psfleet.fleet
    fleet.init(UserDefinedRoleMaker(current_id=0, worker_num=1))
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        ids = fluid.data("ids", shape=[-1, 2], dtype="int64")
        click = fluid.data("click", shape=[-1, 1], dtype="int64")
        emb = fluid.layers.sparse_embedding(
            ids, 8, name="dp_emb", init_range=0.05
        )
        feat = fluid.layers.reduce_sum(emb, dim=1)
        logit = fluid.layers.fc(feat, size=1)
        label_f = fluid.layers.cast(click, "float32")
        loss = fluid.layers.mean(
            fluid.layers.sigmoid_cross_entropy_with_logits(logit, label_f)
        )
        pred = fluid.layers.sigmoid(logit)
        auc_out, (stat_pos, stat_neg) = fluid.layers.auc(pred, click)
        opt = fluid.optimizer.SGD(learning_rate=0.5)
        strategy = psfleet.PSDistributedStrategy(mode="sync", sparse_lr=0.5)
        fleet.distributed_optimizer(opt, strategy).minimize(loss)

    assert main._fleet_opt["device_worker"] == "DownpourSGD"

    # 3. dataset from the files
    ds = fluid.DatasetFactory().create_dataset("InMemoryDataset")
    ds.set_batch_size(32)
    ds.set_use_var([ids, click])
    ds.set_filelist([str(data_file)])
    ds.load_into_memory()
    ds.local_shuffle()

    srv = fleet.init_server(port=0)
    try:
        fleet.init_worker(main)
        exe = fluid.Executor(fluid.CPUPlace())
        with fluid.scope_guard(fluid.Scope()):
            exe.run(startup)
            for _epoch in range(8):
                exe.train_from_dataset(
                    main, ds, fetch_list=[loss], fetch_info=["loss"],
                    print_period=1000,
                )
            util = FleetUtil(fleet)
            auc = util.get_global_auc(stat_pos.name, stat_neg.name)

            # inference: a TRAINING program is refused loudly; the test
            # clone evaluates WITHOUT moving server tables
            from paddle_tpu.utils.enforce import EnforceError

            with pytest.raises(EnforceError, match="for_test"):
                exe.infer_from_dataset(main, ds, fetch_list=[loss])
            probe_ids = np.arange(5, dtype=np.uint64)
            tid = main._sparse_tables["dp_emb"]["table_id"]
            rows_before = fleet._client.pull_sparse(tid, probe_ids, 8).copy()
            test_prog = main.clone(for_test=True)
            exe.infer_from_dataset(test_prog, ds, fetch_list=[loss])
            rows_after = fleet._client.pull_sparse(tid, probe_ids, 8)
            np.testing.assert_array_equal(rows_before, rows_after)
        assert 0.5 < auc <= 1.0, auc
        assert auc > 0.62, f"model did not learn (auc={auc})"
        # sparse rows really live server-side
        assert sum(fleet._client.table_stats().values()) > 0
    finally:
        fleet.stop_worker()
        srv.stop()


def test_embedding_is_distributed_transpiles_to_remote():
    """The reference's port path: embedding(..., is_distributed=True) under
    the PS fleet transpiles to remote in-graph lookups (reference:
    distribute_transpiler.py lookup-table handling) — the local Parameter
    disappears, one table serves MULTIPLE lookups (shared across slots),
    and training moves server-side rows."""
    import warnings

    from paddle_tpu.fleet import parameter_server as psfleet
    from paddle_tpu.fleet.role_maker import UserDefinedRoleMaker

    fleet = psfleet.fleet
    fleet.init(UserDefinedRoleMaker(current_id=0, worker_num=1))
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        a = fluid.data("a", shape=[-1, 2], dtype="int64")
        b = fluid.data("b", shape=[-1, 2], dtype="int64")
        label = fluid.data("label", shape=[-1, 1], dtype="float32")
        # ONE shared is_distributed table feeding two lookups
        ea = fluid.layers.embedding(
            a, size=(1000, 8), is_distributed=True,
            param_attr=fluid.ParamAttr(name="shared_emb"),
        )
        eb = fluid.layers.embedding(
            b, size=(1000, 8), is_distributed=True,
            param_attr=fluid.ParamAttr(name="shared_emb"),
        )
        feat = fluid.layers.concat(
            [fluid.layers.reduce_sum(ea, dim=1),
             fluid.layers.reduce_sum(eb, dim=1)], axis=1)
        logit = fluid.layers.fc(feat, size=1)
        loss = fluid.layers.mean(
            fluid.layers.sigmoid_cross_entropy_with_logits(logit, label))
        strategy = psfleet.PSDistributedStrategy(mode="sync", sparse_lr=0.3)
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            fleet.distributed_optimizer(
                fluid.optimizer.SGD(learning_rate=0.3), strategy
            ).minimize(loss)
        assert any("transpiled" in str(x.message) for x in w)

    # transpile evidence: no local parameter, two remote entries sharing
    # one table, two lookup + two push ops
    assert "shared_emb" not in main.global_block().vars
    entries = list(main._remote_tables.values())
    assert len(entries) == 2
    assert len({e["table_id"] for e in entries}) == 1
    ops = [op.type for op in main.global_block().ops]
    assert ops.count("distributed_lookup_table") == 2
    assert ops.count("distributed_push_sparse") == 2
    assert "lookup_table_v2" not in ops

    srv = fleet.init_server(port=0)
    try:
        fleet.init_worker(main)
        exe = fluid.Executor(fluid.CPUPlace())
        r = np.random.RandomState(0)
        feed = {"a": r.randint(0, 1000, (16, 2)).astype("int64"),
                "b": r.randint(0, 1000, (16, 2)).astype("int64"),
                "label": (r.rand(16, 1) > 0.5).astype("float32")}
        losses = []
        with fluid.scope_guard(fluid.Scope()):
            exe.run(startup)
            for _ in range(10):
                out = exe.run(main, feed=feed, fetch_list=[loss.name])
                losses.append(float(out[0][0]))
        assert losses[-1] < losses[0], losses
        # rows moved server-side; the shared table holds BOTH slots' ids
        stats = fleet._client.table_stats()
        tid = entries[0]["table_id"]
        uniq = len(np.unique(np.concatenate([feed["a"], feed["b"]])))
        assert stats[tid] == uniq, (stats, uniq)
    finally:
        fleet.stop_worker()
        srv.stop()


# ---------------------------------------------------------------------------
# reconnect-on-ConnectionError (PR 8 satellite): the retry policy is
# consulted with its seeded-deterministic backoff, and a permanently dead
# PS surfaces a clear bounded error instead of retrying forever
# ---------------------------------------------------------------------------


import socket
import struct
import threading

from paddle_tpu.resilience.retry import RetryPolicy


class _StubPS(threading.Thread):
    """Minimal Python loopback PS speaking the length-prefixed protocol:
    answers every RPC with status 0 + 4 zero floats. `drop_next` makes it
    close the connection right after reading one request (the mid-RPC
    ConnectionError the client must repair); stop() kills it for the
    permanently-dead case."""

    def __init__(self):
        super().__init__(daemon=True)
        self._srv = socket.create_server(("127.0.0.1", 0))
        self._srv.settimeout(0.2)
        self.endpoint = "127.0.0.1:%d" % self._srv.getsockname()[1]
        self.requests = 0
        self.drop_next = 0
        self._stop = threading.Event()
        self._conns = []

    def run(self):
        while not self._stop.is_set():
            try:
                conn, _ = self._srv.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            self._conns.append(conn)
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()
        self._srv.close()

    def _serve(self, conn):
        try:
            while not self._stop.is_set():
                hdr = b""
                while len(hdr) < 4:
                    chunk = conn.recv(4 - len(hdr))
                    if not chunk:
                        return
                    hdr += chunk
                (blen,) = struct.unpack("<I", hdr)
                body = b""
                while len(body) < blen:
                    body += conn.recv(blen - len(body))
                self.requests += 1
                if self.drop_next > 0:
                    self.drop_next -= 1
                    conn.close()
                    return
                payload = b"\x00" + np.zeros(4, np.float32).tobytes()
                conn.sendall(struct.pack("<I", len(payload)) + payload)
        except OSError:
            pass

    def stop(self):
        self._stop.set()
        try:
            self._srv.close()
        except OSError:
            pass
        for c in self._conns:
            try:
                c.close()
            except OSError:
                pass


def test_psclient_reconnects_with_seeded_backoff():
    """A dropped connection mid-RPC reconnects and resends under the
    retry policy — and the observed backoff sleeps are exactly the
    seeded policy's deterministic schedule (the chaos-replay contract)."""
    srv = _StubPS()
    srv.start()
    try:
        sleeps = []
        policy = RetryPolicy(max_attempts=3, base_delay_s=0.01,
                             max_delay_s=0.1, seed=7,
                             sleep=lambda d: sleeps.append(d))
        client = PSClient([srv.endpoint], retry=policy)
        assert np.array_equal(client.pull_dense(1), np.zeros(4, "f"))
        srv.drop_next = 1
        assert np.array_equal(client.pull_dense(1), np.zeros(4, "f"))
        # one retry happened, after exactly the seeded backoff delay
        assert len(sleeps) == 1
        ref = RetryPolicy(max_attempts=3, base_delay_s=0.01,
                          max_delay_s=0.1, seed=7)
        assert sleeps[0] == pytest.approx(ref.delay(1))
        assert srv.requests == 3  # ok + dropped + resent
        client.close()
    finally:
        srv.stop()


def test_psclient_dead_server_clear_bounded_error():
    """Permanently dead PS: the bounded policy exhausts and the error
    NAMES the endpoint and the attempt budget (no infinite retry, no
    bare socket error)."""
    srv = _StubPS()
    srv.start()
    sleeps = []
    policy = RetryPolicy(max_attempts=3, base_delay_s=0.01,
                         max_delay_s=0.1, seed=7,
                         sleep=lambda d: sleeps.append(d))
    client = PSClient([srv.endpoint], retry=policy)
    assert client.pull_dense(1).shape == (4,)
    srv.stop()
    import time as _time
    _time.sleep(0.3)
    with pytest.raises(ConnectionError) as ei:
        client.pull_dense(1)
    msg = str(ei.value)
    assert srv.endpoint in msg and "3 attempts" in msg, msg
    # bounded: exactly max_attempts - 1 backoffs were taken
    assert len(sleeps) == policy.max_attempts - 1
    client.close()


# ---------------------------------------------------------------------------
# prefetch digest canonicalization (PR 8 satellite): identical id content
# in a different dtype/shape must HIT the prefetched future
# ---------------------------------------------------------------------------


def test_prefetch_digest_canonicalizes_dtype_and_shape():
    from paddle_tpu.distributed import lookup as lk

    class _FakeClient:
        def pull_sparse(self, table_id, uniq, dim):
            return np.stack([np.full(dim, float(i), "f")
                             for i in uniq.tolist()])

        def push_sparse(self, *a):
            pass

    ctx = lk.RemoteLookupContext(_FakeClient())
    ctx.register("t", table_id=1, dim=3)
    try:
        # announced as int64 [B, 1] (the raw feed the driver holds)...
        ids64 = np.array([[5], [9], [5], [2]], dtype=np.int64)
        ctx.prefetch("t", ids64)
        import time as _time
        deadline = _time.monotonic() + 5
        while ctx._pending and not all(
            f.done() for _fence, f in ctx._pending.values()
        ):
            assert _time.monotonic() < deadline
            _time.sleep(0.01)
        # ...pulled by the in-graph callback as int32 [B] (x64 off,
        # squeezed): same content, must be a prefetch HIT
        ids32 = ids64.reshape(-1).astype(np.int32)
        rows = ctx.pull("t", ids32)
        assert ctx.stats["prefetch_hits"] == 1, ctx.stats
        assert ctx.stats["pulls"] == 0, ctx.stats
        assert rows.shape == (4, 3)
        np.testing.assert_array_equal(rows[:, 0], [5.0, 9.0, 5.0, 2.0])
        # digest itself: dtype/shape-insensitive, content-sensitive
        d = lk.RemoteLookupContext._digest
        assert d(ids64) == d(ids32) == d(np.asfortranarray(ids64))
        assert d(ids64) != d(ids64[::-1])
    finally:
        ctx.close()
