"""contrib tests: QAT, DGC, EMA, ModelAverage (reference patterns:
test_quantization_pass.py, test_dgc_optimizer.py, test_ema.py,
test_model_average)."""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.contrib import quantize
from paddle_tpu.core.ir import Program, program_guard


def _linreg(lr=0.05, opt=None):
    x = fluid.data("x", shape=[-1, 8])
    y = fluid.data("y", shape=[-1, 1])
    pred = fluid.layers.fc(x, size=1)
    loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
    return x, y, pred, loss


def test_ema_shadow_tracks_params(rng):
    main, startup = Program(), Program()
    with program_guard(main, startup):
        _, _, _, loss = _linreg()
        fluid.optimizer.SGD(0.1).minimize(loss)
        ema = fluid.optimizer.ExponentialMovingAverage(0.5)
        ema.update()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    x = rng.rand(32, 8).astype("float32")
    y = x.sum(1, keepdims=True).astype("float32")
    for _ in range(10):
        exe.run(main, feed={"x": x, "y": y}, fetch_list=[loss])
    scope = fluid.global_scope()
    pname = main.all_parameters()[0].name
    raw = np.asarray(scope.find_var(pname))
    with ema.apply():
        shadow_applied = np.asarray(scope.find_var(pname))
    restored = np.asarray(scope.find_var(pname))
    assert not np.allclose(raw, shadow_applied)  # EMA lags training
    np.testing.assert_array_equal(raw, restored)  # restored on exit
    # the shadow should be an average-ish of parameter history: closer to
    # zero-init than the latest value
    assert np.abs(shadow_applied).sum() < np.abs(raw).sum() + 1e-6


def test_model_average_apply_restore(rng):
    main, startup = Program(), Program()
    with program_guard(main, startup):
        _, _, _, loss = _linreg()
        fluid.optimizer.SGD(0.1).minimize(loss)
        avg = fluid.optimizer.ModelAverage(max_average_window=100)
        avg.minimize_after()
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    x = rng.rand(32, 8).astype("float32")
    y = x.sum(1, keepdims=True).astype("float32")
    snaps = []
    for _ in range(5):
        exe.run(main, feed={"x": x, "y": y}, fetch_list=[loss])
        pname = main.all_parameters()[0].name
        snaps.append(np.asarray(fluid.global_scope().find_var(pname)))
    mean = np.mean(snaps, axis=0)
    with avg.apply():
        applied = np.asarray(fluid.global_scope().find_var(pname))
    np.testing.assert_allclose(applied, mean, rtol=1e-5, atol=1e-6)


def test_dgc_converges_and_sparsifies(rng):
    main, startup = Program(), Program()
    with program_guard(main, startup):
        _, _, _, loss = _linreg()
        opt = fluid.optimizer.DGCMomentumOptimizer(
            learning_rate=0.05, momentum=0.9,
            rampup_begin_step=3, rampup_step=4, sparsity=[0.5, 0.75],
        )
        opt.minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    x = rng.rand(64, 8).astype("float32")
    y = x.sum(1, keepdims=True).astype("float32")
    losses = [
        float(exe.run(main, feed={"x": x, "y": y}, fetch_list=[loss])[0][0])
        for _ in range(40)
    ]
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])
    # error-feedback accumulator must be non-trivial once sparsity kicks in
    scope = fluid.global_scope()
    vnames = [n for n in scope.var_names() if "dgc_v" in n]
    assert vnames


def test_dgc_dense_phase_matches_momentum(rng):
    """Before rampup_begin_step DGC must equal plain momentum."""
    x = rng.rand(32, 8).astype("float32")
    y = x.sum(1, keepdims=True).astype("float32")

    def run(opt):
        main, startup = Program(), Program()
        with program_guard(main, startup):
            _, _, _, loss = _linreg()
            opt().minimize(loss)
        exe = fluid.Executor(fluid.CPUPlace())
        with fluid.scope_guard(fluid.Scope()):
            exe.run(startup)
            return [
                float(exe.run(main, feed={"x": x, "y": y}, fetch_list=[loss])[0][0])
                for _ in range(5)
            ]

    ref = run(lambda: fluid.optimizer.Momentum(0.05, 0.9))
    got = run(lambda: fluid.optimizer.DGCMomentumOptimizer(
        0.05, 0.9, rampup_begin_step=1000))
    np.testing.assert_allclose(ref, got, rtol=1e-5, atol=1e-6)


def test_qat_inserts_fake_quant_and_trains(rng):
    main, startup = Program(), Program()
    with program_guard(main, startup):
        x = fluid.data("x", shape=[-1, 8])
        y = fluid.data("y", shape=[-1, 1])
        h = fluid.layers.fc(x, size=16, act="relu")
        pred = fluid.layers.fc(h, size=1)
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
        quantize.quantize_program(main, startup)
        fluid.optimizer.Adam(1e-2).minimize(loss)
    types = [op.type for op in main.global_block().ops]
    assert "fake_quantize_dequantize_abs_max" in types          # weights
    assert "fake_quantize_dequantize_moving_average_abs_max" in types  # acts
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    xv = rng.rand(64, 8).astype("float32")
    yv = xv.sum(1, keepdims=True).astype("float32")
    losses = [
        float(exe.run(main, feed={"x": xv, "y": yv}, fetch_list=[loss])[0][0])
        for _ in range(30)
    ]
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])
    # activation scale state must have been learned
    scope = fluid.global_scope()
    scales = [n for n in scope.var_names() if ".scale" in n]
    assert scales and all(
        float(np.asarray(scope.find_var(n)).reshape(-1)[0]) > 0 for n in scales
    )


def test_qat_convert_freezes_scales(rng):
    main, startup = Program(), Program()
    with program_guard(main, startup):
        x = fluid.data("x", shape=[-1, 4])
        pred = fluid.layers.fc(x, size=2)
        quantize.quantize_program(main, startup)
    test_prog = quantize.convert_to_test(main)
    for op in test_prog.global_block().ops:
        if op.type == "fake_quantize_dequantize_moving_average_abs_max":
            assert op.attrs["is_test"] is True
    # original program untouched
    for op in main.global_block().ops:
        if op.type == "fake_quantize_dequantize_moving_average_abs_max":
            assert not op.attrs.get("is_test", False)


def test_quantized_weights_have_limited_levels(rng):
    """Fake-quantized values must land on <= 2^bits distinct levels."""
    import jax.numpy as jnp

    from paddle_tpu.contrib.quantize import _fq_abs_max

    x = rng.randn(64, 32).astype("float32")
    out = np.asarray(
        _fq_abs_max({"X": [jnp.asarray(x)]}, {"bit_length": 4})["Out"][0]
    )
    assert len(np.unique(out)) <= 2 ** 4
    assert abs(out).max() <= abs(x).max() + 1e-6


def test_pipeline_optimizer_matches_large_batch(rng):
    """Microbatched grad accumulation must match the full-batch step when
    the loss is a mean over examples (linear model => exact)."""
    x = rng.rand(32, 8).astype("float32")
    y = x.sum(1, keepdims=True).astype("float32")

    def run(wrap):
        main, startup = Program(), Program()
        with program_guard(main, startup):
            _, _, _, loss = _linreg()
            opt = fluid.optimizer.SGD(0.1)
            if wrap:
                opt = fluid.optimizer.PipelineOptimizer(opt, num_microbatches=4)
            opt.minimize(loss)
        exe = fluid.Executor(fluid.CPUPlace())
        with fluid.scope_guard(fluid.Scope()):
            exe.run(startup)
            out = []
            for _ in range(4):
                exe.run(main, feed={"x": x, "y": y}, fetch_list=[loss])
                pname = main.all_parameters()[0].name
            return np.asarray(fluid.global_scope().find_var(pname))

    ref = run(False)
    got = run(True)
    np.testing.assert_allclose(ref, got, rtol=1e-5, atol=1e-6)


def test_pipeline_persistables_chain_across_microbatches(rng):
    """Forward-written persistables (batch-norm moving stats) must see every
    microbatch, chaining mb-to-mb like the reference's shared-scope section
    pipeline — not reset so only the last microbatch's update survives."""
    num_mb, mb_sz, feat = 4, 8, 3
    momentum = 0.5
    x = rng.rand(num_mb * mb_sz, feat).astype("float32")

    main, startup = Program(), Program()
    with program_guard(main, startup):
        xv = fluid.data("x", [num_mb * mb_sz, feat])
        h = fluid.layers.batch_norm(
            xv, momentum=momentum, moving_mean_name="pipe_mm"
        )
        loss = fluid.layers.reduce_mean(h)
        opt = fluid.optimizer.PipelineOptimizer(
            fluid.optimizer.SGD(0.0), num_microbatches=num_mb
        )
        opt.minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        exe.run(main, feed={"x": x}, fetch_list=[loss])
        got = np.asarray(fluid.global_scope().find_var("pipe_mm"))

    # reference: chain the moving-mean update through every microbatch
    mm = np.zeros(feat, "float32")
    for m in range(num_mb):
        bmean = x[m * mb_sz:(m + 1) * mb_sz].mean(0)
        mm = mm * momentum + bmean * (1 - momentum)
    np.testing.assert_allclose(got, mm, rtol=1e-5, atol=1e-6)
