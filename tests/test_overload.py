"""Graceful degradation under pressure (ISSUE 18).

The acceptance contract: block-pool exhaustion NEVER silently loses or
needlessly fails work — sessions that cannot keep their arena rows park
(KV spilled to the host-RAM tier, slot freed) and later resume
byte-identical to an uninterrupted run, in every generation mode and
for every victim-selection policy; a corrupted host-tier entry is
quarantined by its CRC and the resume recomputes the KV from the token
history instead of reading garbage; admission defers (measured
retry-after) rather than hard-failing unless the request can NEVER fit;
the brownout ladder escalates immediately, de-escalates hysteretically,
and its two REJECT rungs (L4 shed, L3 beam cap) only fire while live
pressure confirms the severity; and the committed
OVERLOAD_EVIDENCE_r18.json re-derives live.
"""

import importlib.util
import json
import os

import pytest

from paddle_tpu.serving.brownout import BrownoutController
from paddle_tpu.serving.decode import (
    GenerationEngine,
    SamplingParams,
    build_decoder_model,
)
from paddle_tpu.serving.decode.tier import HostKVTier
from paddle_tpu.serving.request import (
    Priority,
    RejectedError,
    RequestError,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _tight_model(name, slots=2, num_blocks=6, max_len=16, block_size=2):
    return build_decoder_model(
        vocab_size=32, hidden=8, num_layers=1, slots=slots,
        max_len=max_len, block_size=block_size, num_blocks=num_blocks,
        name=name, version="1")


def _drain(entry, resps, iters=800):
    for _ in range(iters):
        if all(r.done() for r in resps):
            return
        entry._iterate()
    raise AssertionError("hand-stepped drain did not converge")


# ---------------------------------------------------------------------------
# host KV tier (unit)
# ---------------------------------------------------------------------------


def test_host_tier_put_get_lru_and_capacity():
    import numpy as np

    tier = HostKVTier(capacity_bytes=1024)   # 4 entries of 256 B
    rows = [(np.ones((4, 8), "float32"), np.ones((4, 8), "float32"))]
    assert tier.put("blk:a", rows, 4, tokens=(1, 2, 3, 4))
    assert "blk:a" in tier and len(tier) == 1
    ent = tier.get("blk:a")
    assert ent is not None and ent.size_used == 4
    assert np.array_equal(ent.kv_rows[0][0], rows[0][0])
    # LRU: filling past capacity evicts the stalest entry, never errors
    for i in range(8):
        assert tier.put(f"blk:{i}", rows, 4, tokens=(i,))
    assert "blk:a" not in tier
    assert tier.stats()["evictions"] >= 1
    # an entry that ALONE exceeds the budget is the only refusal
    tiny = HostKVTier(capacity_bytes=8)
    assert not tiny.put("blk:x", rows, 4, tokens=(1,))
    assert tiny.stats()["rejected"] == 1


def test_host_tier_crc_quarantines_corruption():
    import numpy as np

    tier = HostKVTier(capacity_bytes=1 << 20)
    rows = [(np.arange(32, dtype="float32").reshape(4, 8),
             np.zeros((4, 8), "float32"))]
    tier.put("park:7:0", rows, 4, tokens=(1, 2, 3, 4))
    assert tier.stats()["spills"] == 1       # park: keys count as spills
    tier.corrupt_entry("park:7:0")
    # a corrupt entry reads as a MISS, never as wrong bytes
    assert tier.pop("park:7:0") is None
    st = tier.stats()
    assert st["corrupt_dropped"] == 1 and st["misses"] == 1
    assert "park:7:0" not in tier


# ---------------------------------------------------------------------------
# brownout controller (unit, hand-stepped, no threads)
# ---------------------------------------------------------------------------


def test_brownout_escalates_immediately_to_highest_rung():
    ctl = BrownoutController()
    assert ctl.step(occupancy=0.2) == 0
    assert ctl.step(occupancy=0.97) == 4     # straight to L4, no ladder
    (t,) = ctl.transitions
    assert t["from"] == 0 and t["to"] == 4
    assert t["trigger"] == "occupancy" and t["value"] == 0.97


def test_brownout_deescalates_one_level_per_hold_window():
    ctl = BrownoutController(hold=3)
    ctl.step(occupancy=0.97)
    for expect in (4, 4, 3):                 # 3 clear steps -> one level
        assert ctl.step(occupancy=0.1) == expect
    for expect in (3, 3, 2):
        assert ctl.step(occupancy=0.1) == expect


def test_brownout_hysteresis_band_holds_without_flapping():
    ctl = BrownoutController()               # enter[2]=0.85, exit[2]=0.70
    ctl.step(occupancy=0.9)                  # -> L3
    assert ctl.level == 3
    for _ in range(10):                      # inside the band: no motion
        assert ctl.step(occupancy=0.75) == 3
    assert len(ctl.transitions) == 1


def test_brownout_clear_streak_resets_on_pressure_blip():
    ctl = BrownoutController(hold=3)
    ctl.step(occupancy=0.97)
    ctl.step(occupancy=0.1)
    ctl.step(occupancy=0.1)
    ctl.step(occupancy=0.9)                  # blip: streak must reset
    for expect in (4, 4, 3):
        assert ctl.step(occupancy=0.1) == expect


def test_brownout_trigger_names_the_binding_signal():
    ctl = BrownoutController()
    ctl.step(occupancy=0.3, queue_seconds=0.96, deadline=0.5)
    assert ctl.transitions[-1]["trigger"] == "queue_seconds"


# ---------------------------------------------------------------------------
# preemption / resume
# ---------------------------------------------------------------------------


def _victim_policies():
    return {
        "default": None,                         # newest admission
        "oldest": lambda cands: min(cands, key=lambda s: s.seq),
        "shuffled": lambda cands: sorted(
            cands, key=lambda s: (s.seq * 2654435761) % 97)[0],
    }


@pytest.mark.parametrize("policy", sorted(_victim_policies()))
def test_preempt_resume_bit_identity_any_victim(policy):
    """Four sessions against a pool that serves ~two: whichever victim
    the policy picks, every stream finishes byte-identical to the
    uninterrupted offline reference, nothing fails, and the pool
    conserves."""
    engine = GenerationEngine(queue_depth=16, breaker_threshold=0)
    entry = engine.register_model(
        lambda: _tight_model(f"ov_vic_{policy}", slots=3, num_blocks=8))
    entry.victim_policy = _victim_policies()[policy]
    prompts = [[1 + i, 2 + i, 3 + i, 4 + i] for i in range(4)]
    refs = [entry.offline_decode(p, 6) for p in prompts]
    resps = [engine.submit(p, max_new_tokens=6) for p in prompts]
    _drain(entry, resps)
    outs = [[int(t) for t in r.result(timeout=60)["tokens"]]
            for r in resps]
    st = entry.stats()
    engine.shutdown()
    assert outs == refs
    assert st["failed"] == 0
    assert st["sessions_parked"] >= 1
    assert st["sessions_parked"] == st["sessions_resumed"]
    entry.block_pool.check_conservation()


def test_preempt_resume_sampled_stream_bit_identity():
    """The committed threefry stream is keyed per (seed, emitted index)
    — a park/resume in the middle of it must not advance or rewind a
    single draw."""
    engine = GenerationEngine(queue_depth=16, breaker_threshold=0)
    entry = engine.register_model(lambda: _tight_model("ov_samp"))
    sp = SamplingParams(temperature=0.8, top_k=6, seed=11)
    prompts = [[1, 2, 3, 4], [5, 6, 7, 8]]
    refs = [entry.offline_decode(p, 6, sampling=sp) for p in prompts]
    resps = [engine.submit(p, max_new_tokens=6, sampling=sp)
             for p in prompts]
    _drain(entry, resps)
    outs = [[int(t) for t in r.result(timeout=60)["tokens"]]
            for r in resps]
    st = entry.stats()
    engine.shutdown()
    assert outs == refs and st["sessions_parked"] >= 1


def test_corruption_walkback_recomputes_not_garbage():
    """Flip one byte of a parked session's host-tier entry: the CRC
    quarantine must turn the resume into a replay-recompute
    (``resume_replays``) — same bytes out, one counter up."""
    engine = GenerationEngine(queue_depth=16, breaker_threshold=0)
    entry = engine.register_model(lambda: _tight_model("ov_crc_t"))
    prompts = [[1, 2, 3, 4], [5, 6, 7, 8]]
    refs = [entry.offline_decode(p, 6) for p in prompts]
    resps = [engine.submit(p, max_new_tokens=6) for p in prompts]
    corrupted = False
    for _ in range(800):
        if all(r.done() for r in resps):
            break
        if entry._parked and not corrupted:
            for key in entry._parked[0].keys:
                entry._tier.corrupt_entry(key)
            corrupted = True
        entry._iterate()
    outs = [[int(t) for t in r.result(timeout=60)["tokens"]]
            for r in resps]
    st = entry.stats()
    engine.shutdown()
    assert corrupted, "no session ever parked — the test proved nothing"
    assert outs == refs
    assert st["resume_replays"] >= 1
    assert st["host_tier"]["corrupt_dropped"] >= 1


def test_admission_defers_until_capacity_then_completes():
    """2x-capacity burst: every accepted request completes — exhaustion
    parks or defers, it never fails a request that can fit."""
    engine = GenerationEngine(queue_depth=16, breaker_threshold=0)
    entry = engine.register_model(lambda: _tight_model("ov_defer"))
    prompts = [[1 + i, 2 + i, 3 + i, 4 + i] for i in range(4)]
    refs = [entry.offline_decode(p, 6) for p in prompts]
    resps = [engine.submit(p, max_new_tokens=6) for p in prompts]
    _drain(entry, resps)
    outs = [[int(t) for t in r.result(timeout=60)["tokens"]]
            for r in resps]
    st = entry.stats()
    engine.shutdown()
    assert outs == refs
    assert st["failed"] == 0 and st["completed"] == len(prompts)
    assert st["blocks_failed_total"] == 0


def test_never_fit_prompt_still_fails_loudly():
    """The ONE legitimate hard failure: a prompt whose blocks exceed
    the whole pool can never be served — parking everyone else would
    not help, so it fails loudly at admission, attributed."""
    engine = GenerationEngine(queue_depth=16, breaker_threshold=0)
    entry = engine.register_model(lambda: _tight_model("ov_neverfit"))
    with pytest.raises(RequestError, match="can never fit"):
        r = engine.submit(list(range(1, 14)), max_new_tokens=2)
        _drain(entry, [r])
        r.result(timeout=60)
    assert entry.metrics.count("blocks_failed_total") == 1
    engine.shutdown()


# ---------------------------------------------------------------------------
# the two REJECT rungs: stale severity must not shed
# ---------------------------------------------------------------------------


def test_l4_shed_requires_live_pressure():
    engine = GenerationEngine(queue_depth=16, breaker_threshold=0)
    entry = engine.register_model(lambda: _tight_model("ov_shed"))
    entry._brownout.level = 4
    # severity says shed, but the engine is idle: admission must pass
    r = engine.submit([1, 2], max_new_tokens=2)
    _drain(entry, [r])
    assert [int(t) for t in r.result(timeout=60)["tokens"]]
    # now live pressure confirms it: non-HIGH is turned away with a
    # measured retry-after, HIGH still lands
    entry._pending.append(object())
    try:
        with pytest.raises(RejectedError) as exc:
            engine.submit([1, 2], max_new_tokens=2)
        assert exc.value.retry_after_s is not None
        assert entry.metrics.count("brownout_shed") == 1
        high = engine.submit([1, 2], max_new_tokens=2,
                             priority=Priority.HIGH)
    finally:
        entry._pending.pop()
    entry._brownout.level = 0
    _drain(entry, [high])
    assert [int(t) for t in high.result(timeout=60)["tokens"]]
    engine.shutdown()


def test_l3_beam_cap_requires_live_pressure():
    engine = GenerationEngine(queue_depth=16, breaker_threshold=0)
    entry = engine.register_model(
        lambda: _tight_model("ov_cap", slots=3, num_blocks=12))
    entry._brownout.level = 3
    # idle engine: a wide beam admits despite the stale severity
    r = engine.submit([1, 2], max_new_tokens=2, beam_width=3)
    _drain(entry, [r])
    assert r.result(timeout=60)["beams"]
    entry._pending.append(object())
    try:
        with pytest.raises(RejectedError, match="beam width capped"):
            engine.submit([1, 2], max_new_tokens=2, beam_width=3)
        # at or under the cap still admits
        ok = engine.submit([1, 2], max_new_tokens=2, beam_width=2)
    finally:
        entry._pending.pop()
    entry._brownout.level = 0
    _drain(entry, [ok])
    assert ok.result(timeout=60)["beams"]
    engine.shutdown()


# ---------------------------------------------------------------------------
# evidence drift gate
# ---------------------------------------------------------------------------


def test_overload_evidence_r18_committed():
    """The committed overload evidence must re-derive LIVE: the
    hand-stepped preemption/corruption/ledger legs and the scripted
    brownout trace reproduce exactly the committed invariants section.
    Drift means the degradation machinery changed behavior without
    regenerating evidence: run `python tools/overload_report.py
    --evidence OVERLOAD_EVIDENCE_r18.json`."""
    path = os.path.join(REPO, "OVERLOAD_EVIDENCE_r18.json")
    assert os.path.exists(path), "OVERLOAD_EVIDENCE_r18.json missing"
    with open(path) as f:
        committed = json.load(f)
    tool = _load_tool("overload_report")
    invariants, _measured = tool.deterministic_sections()
    fresh = json.loads(json.dumps(invariants))
    assert tool.check_invariants(fresh) == []
    for key in ("preemption", "corruption", "ledger", "brownout"):
        assert fresh[key] == committed["invariants"][key], (
            f"overload evidence drift in '{key}'")
