"""Continuous-batching decode engine (paddle_tpu/serving/decode).

The acceptance contract (ISSUE 10, extended by ISSUE 13 to the paged
rebuild): generation through the iteration-level scheduler is
bit-identical to offline whole-sequence decode for the same prompts
REGARDLESS of admission order, slot assignment, what the other slots
are doing, or MODE — paged block storage, chunked prefill, speculative
decoding with greedy acceptance; prompts sharing a prefix share
PHYSICAL blocks (radix tree, copy-on-write at divergence); a killed
replica is re-admitted by the circuit breaker as an AOT-warmed
replacement with zero recompiles; a fresh process restores all three
default executables (decode step / prefill / inject) from the
compile-cache disk tier with zero traces — subprocess-asserted like
tests/test_compile_cache.py; and the committed perf evidence
(DECODE_EVIDENCE_r13.json: static peak-HBM paged-vs-slotted, block
dedup ratio, speculative steps-per-token) re-derives live.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from paddle_tpu.resilience import faults
from paddle_tpu.serving.decode import (
    GenerationEngine,
    GenerationRequest,
    build_decoder_model,
)
from paddle_tpu.serving.queue import RequestQueue
from paddle_tpu.serving.request import (
    DeadlineExceededError,
    Priority,
    RejectedError,
    RequestError,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "decode_worker.py")


def _small_model(name="dec", version="1", slots=4, max_len=16, eos_id=None):
    return build_decoder_model(
        vocab_size=32, hidden=8, num_layers=2, slots=slots,
        max_len=max_len, eos_id=eos_id, name=name, version=version,
    )


@pytest.fixture(scope="module")
def served():
    """One warm engine + entry shared by the read-mostly tests."""
    engine = GenerationEngine(queue_depth=64, breaker_threshold=0)
    entry = engine.register_model(
        lambda: _small_model(name="shared", slots=4, max_len=16))
    engine.start()
    yield engine, entry
    engine.shutdown()


# ---------------------------------------------------------------------------
# bit-exactness: continuous == offline under arbitrary interleavings
# ---------------------------------------------------------------------------


def test_continuous_decode_matches_offline_any_admission_order(served):
    """10 mixed-length prompts, submitted in shuffled orders with jittered
    arrivals and mixed priorities over a 4-slot batch: every request's
    tokens equal the offline whole-sequence reference, although slot
    assignment and batchmates differ per round (retirement order
    permutes the free-slot list between rounds)."""
    engine, entry = served
    rng = np.random.RandomState(7)
    prompts = [list(rng.randint(0, 32, size=rng.randint(1, 7)))
               for _ in range(10)]
    max_news = [int(rng.randint(1, 9)) for _ in range(10)]
    refs = [entry.offline_decode(p, n) for p, n in zip(prompts, max_news)]

    for round_seed in (0, 1):
        order = np.random.RandomState(round_seed).permutation(10)
        resps = {}
        for i in order:
            resps[int(i)] = engine.submit(
                prompts[i], max_new_tokens=max_news[i],
                priority=int(i) % 3,
            )
            if int(i) % 3 == 0:
                time.sleep(0.002)  # stagger arrivals across iterations
        for i, r in resps.items():
            got = [int(t) for t in r.result(timeout=120)["tokens"]]
            assert got == refs[i], (
                f"round {round_seed} prompt {i}: continuous {got} != "
                f"offline {refs[i]}")


def test_decode_modes_bit_identical_kernels_on_vs_off():
    """Paged, chunked, and speculative decode under the kernel registry's
    "interpret" mode (Pallas kernels through the interpreter) vs "off"
    (composite fallbacks), with SHUFFLED admission orders: every
    request's tokens equal the offline reference, and the two modes are
    byte-identical to each other — the fused paged-attention kernel is
    the exact composite primitive sequence, held here against the real
    engine."""
    from paddle_tpu import kernels

    rng = np.random.RandomState(11)
    prompts = [list(int(t) for t in rng.randint(0, 32, size=n))
               for n in (9, 8, 2, 12, 5)]
    max_news = [5, 6, 4, 5, 6]

    def drive(mode, order_seed):
        with kernels.scoped_mode(mode):
            engine = GenerationEngine(queue_depth=32, breaker_threshold=0)
            entry = engine.register_model(lambda: build_decoder_model(
                vocab_size=32, hidden=8, num_layers=2, slots=4,
                max_len=24, block_size=4, chunk_tokens=4,
                name="kmode", version="1"))
            engine.register_model(lambda: build_decoder_model(
                vocab_size=32, hidden=8, num_layers=2, slots=4,
                max_len=24, block_size=4, name="kmode_d", version="1"))
            refs = [entry.offline_decode(p, n)
                    for p, n in zip(prompts, max_news)]
            order = np.random.RandomState(order_seed).permutation(
                len(prompts))
            resps = {}
            for i in order:
                resps[int(i)] = engine.submit(
                    prompts[i], max_new_tokens=max_news[i], model="kmode")
            spec = engine.submit(prompts[0], max_new_tokens=5,
                                 model="kmode", draft_model="kmode_d",
                                 spec_k=2)
            for _ in range(300):
                if spec.done() and all(r.done() for r in resps.values()):
                    break
                entry._iterate()
            outs = [
                [int(t) for t in resps[i].result(timeout=120)["tokens"]]
                for i in range(len(prompts))
            ]
            assert outs == refs, f"mode {mode}: continuous != offline"
            outs.append(
                [int(t) for t in spec.result(timeout=120)["tokens"]])
            engine.shutdown()
            return outs

    # different admission orders per mode pair: bit-identity must hold
    # regardless of slot assignment/batchmates (the PR-13 property)
    assert drive("off", 0) == drive("interpret", 1)
    assert drive("interpret", 2) == drive("off", 3)


def test_eos_and_arena_edge_finish_rules_match_offline():
    """eos stop and prompt-fills-arena edge both fire identically in the
    continuous and offline paths (the finish rules are the contract,
    not an implementation detail). The eos token is probed from what the
    greedy head ACTUALLY generates (eos_id is host-side policy, so the
    probe model and the served model share byte-identical programs and
    weights under the same (name, version) prefix)."""
    prompt = [1, 2, 3]
    probe = GenerationEngine(queue_depth=16, breaker_threshold=0)
    free_run = probe.register_model(
        lambda: _small_model(name="eos", slots=2, max_len=10)
    ).offline_decode(prompt, 6)
    assert len(free_run) == 6  # nothing stops it without an eos rule
    # first token whose first occurrence is mid-stream: stopping on it is
    # observable (shorter than the free run) and unambiguous (index 0 of
    # that token IS the stop point)
    eos_at = next((j for j in range(1, len(free_run) - 1)
                   if free_run[j] not in free_run[:j]), 0)
    eos_id = free_run[eos_at]

    engine = GenerationEngine(queue_depth=16, breaker_threshold=0)
    entry = engine.register_model(
        lambda: _small_model(name="eos", slots=2, max_len=10, eos_id=eos_id))
    engine.start()
    try:
        want = entry.offline_decode(prompt, 6)
        assert want == free_run[:eos_at + 1]  # stopped early, ON the eos
        got = [int(t) for t in engine.submit(
            prompt, max_new_tokens=6).result(timeout=120)["tokens"]]
        assert got == want and got[-1] == eos_id
        # arena edge: prompt + max_new fills the KV arena exactly
        edge = [4, 5, 6, 7]
        assert engine.submit(edge, max_new_tokens=6).result(
            timeout=120)["tokens"].shape[0] <= 6
        assert [int(t) for t in engine.submit(edge, max_new_tokens=6)
                .result(timeout=120)["tokens"]] == entry.offline_decode(edge, 6)
    finally:
        engine.shutdown()


def test_prefix_cache_dedups_prefill_bit_exactly(served):
    """Two requests with the same prompt pay ONE prefill forward; the
    cache-hit admission generates the same tokens as the miss."""
    engine, entry = served
    prompt = [9, 9, 8, 7]
    hits0 = entry.prefix_cache.hits
    prefills0 = entry.metrics.count("prefills")
    r1 = engine.submit(prompt, max_new_tokens=5)
    out1 = [int(t) for t in r1.result(timeout=120)["tokens"]]
    r2 = engine.submit(prompt, max_new_tokens=5)
    out2 = [int(t) for t in r2.result(timeout=120)["tokens"]]
    assert out1 == out2 == entry.offline_decode(prompt, 5)
    assert entry.prefix_cache.hits >= hits0 + 1
    assert entry.metrics.count("prefills") == prefills0 + 1


# ---------------------------------------------------------------------------
# multi-tenant registry + weighted-fair scheduling
# ---------------------------------------------------------------------------


def _queued(queue, rid, tenant, priority=Priority.NORMAL):
    req = GenerationRequest(rid, [1], 4, tenant, priority, None)
    queue.put(req)
    return req


def _pick_locked(engine, queue):
    """_pick's documented contract: the caller holds queue.lock (the
    scheduler calls it under its dispatch Condition). The lockdep witness
    enforces the declared serving.queue -> decode.tenant order, so the
    hand-stepped tests must honor the contract too."""
    with queue.lock:
        return engine._pick(queue)


def test_weighted_fair_pick_honors_stride_shares():
    """Under contention, a weight-2 tenant wins two slots for every one a
    weight-1 tenant wins (deterministic stride scheduling on the picker,
    no engine threads involved)."""
    engine = GenerationEngine(breaker_threshold=0)
    engine.set_tenant("a", weight=2.0)
    engine.set_tenant("b", weight=1.0)
    queue = RequestQueue(max_depth=256)
    for i in range(60):
        _queued(queue, i, "a" if i % 2 == 0 else "b")
    wins = {"a": 0, "b": 0}
    for _ in range(30):
        wins[_pick_locked(engine, queue).tenant] += 1
    assert wins["a"] == 20 and wins["b"] == 10, wins


def test_pick_strict_priority_lanes_before_fairness():
    """Lane order dominates: a HIGH request dispatches before NORMAL
    traffic even when its tenant is far behind on virtual time."""
    engine = GenerationEngine(breaker_threshold=0)
    engine.set_tenant("busy", weight=1.0)
    queue = RequestQueue(max_depth=64)
    for i in range(4):
        _queued(queue, i, "busy")
        _pick_locked(engine, queue)  # banks virtual time for 'busy'
    _queued(queue, 100, "fresh")                      # NORMAL lane
    _queued(queue, 101, "busy", priority=Priority.HIGH)
    assert _pick_locked(engine, queue).id == 101


def test_pick_skips_tenant_at_in_flight_cap():
    engine = GenerationEngine(breaker_threshold=0)
    engine.set_tenant("capped", weight=10.0, max_in_flight=1)
    engine._tenant("capped").in_flight = 1
    queue = RequestQueue(max_depth=64)
    _queued(queue, 1, "capped")
    _queued(queue, 2, "other")
    assert _pick_locked(engine, queue).tenant == "other"
    # only the capped tenant queued -> nothing admissible, req stays queued
    assert _pick_locked(engine, queue) is None
    engine._tenant("capped").in_flight = 0
    assert _pick_locked(engine, queue).tenant == "capped"


def test_pick_reserves_in_flight_so_one_round_cannot_exceed_cap():
    """An admission round with several free slots calls _pick repeatedly
    BEFORE any prefill runs; the cap must be charged at pick time or one
    round admits a capped tenant twice."""
    engine = GenerationEngine(breaker_threshold=0)
    engine.set_tenant("capped", weight=1.0, max_in_flight=1)
    queue = RequestQueue(max_depth=64)
    _queued(queue, 1, "capped")
    _queued(queue, 2, "capped")
    first = _pick_locked(engine, queue)
    assert first.tenant == "capped"
    assert engine._tenant("capped").in_flight == 1
    # same round, second free slot: the reservation blocks the pick
    assert _pick_locked(engine, queue) is None
    # retire the first -> the second request becomes admissible
    engine._tenant_unflight("capped")
    assert _pick_locked(engine, queue).id == 2


def test_idle_tenant_reenters_at_vtime_floor():
    """A long-idle tenant must not burn banked lag into a burst that
    starves everyone else: it re-enters at the current floor and still
    alternates with the active tenant."""
    engine = GenerationEngine(breaker_threshold=0)
    engine.set_tenant("active", weight=1.0)
    engine.set_tenant("idle", weight=1.0)
    queue = RequestQueue(max_depth=256)
    for i in range(10):
        _queued(queue, i, "active")
        _pick_locked(engine, queue)  # active's vtime climbs to 10
    for i in range(10, 18):
        _queued(queue, i, "active" if i % 2 == 0 else "idle")
    picks = [_pick_locked(engine, queue).tenant for _ in range(8)]
    # never more than 2 consecutive wins for the returning tenant
    for k in range(len(picks) - 2):
        assert len(set(picks[k:k + 3])) > 1, picks


def test_quota_reject_on_live_engine_does_not_deadlock():
    """Over-quota submits while the scheduler loop is dispatching: the
    quota path must estimate retry-after OUTSIDE _tenant_lock (the loop
    acquires queue-lock -> tenant-lock; holding tenant-lock while taking
    the queue lock was an ABBA deadlock)."""
    engine = GenerationEngine(queue_depth=64, breaker_threshold=0)
    entry = engine.register_model(
        lambda: _small_model(name="livequota", slots=1, max_len=32))
    engine.set_tenant("q", max_queued=1)
    engine.start()
    try:
        keep = [engine.submit([1, 2], tenant="q", max_new_tokens=24)]
        rejected = 0
        for _ in range(200):  # race the scheduler's admission scans
            try:
                keep.append(engine.submit([1, 2], tenant="q",
                                          max_new_tokens=2))
            except RejectedError as e:
                assert e.retry_after_s > 0.0
                rejected += 1
        assert rejected > 0
        for r in keep:
            r.result(timeout=120)
    finally:
        engine.shutdown()
    assert entry.metrics.count("rejected_quota") == rejected


def test_inject_failure_invalidates_arena_and_recovers():
    """A failed DONATED inject is replica health, not a request error:
    the admitting request and every in-flight sequence fail loudly, the
    arena resets, and the next request generates bit-identically."""
    engine = GenerationEngine(queue_depth=16, breaker_threshold=0)
    entry = engine.register_model(
        lambda: _small_model(name="inj", slots=2, max_len=32))
    ref = entry.offline_decode([5, 6], 4)
    engine.start()
    try:
        victim = engine.submit([1, 2], max_new_tokens=24)  # holds slot 0
        deadline = time.time() + 30
        while entry.stats()["active_slots"] < 1:
            assert time.time() < deadline
            time.sleep(0.002)
        faults.configure([{"site": "decode.inject", "action": "raise",
                           "times": 1}])
        doomed = engine.submit([3, 4], max_new_tokens=4)
        with pytest.raises(RequestError, match="failed in inject"):
            doomed.result(timeout=120)
        with pytest.raises(RequestError, match="arena failure"):
            victim.result(timeout=120)
        out = engine.submit([5, 6], max_new_tokens=4).result(timeout=120)
        assert [int(t) for t in out["tokens"]] == ref
    finally:
        engine.shutdown()
        faults.reset()
    assert entry.stats()["step_failures"] == 1


def test_arena_failure_mid_admission_still_admits_remaining_picked():
    """When the FIRST of several picked requests invalidates the arena,
    the rest must still admit into the reset arena — dropping them would
    abandon their futures forever and leak tenant queued counters."""
    engine = GenerationEngine(queue_depth=16, breaker_threshold=0)
    entry = engine.register_model(
        lambda: _small_model(name="multi", slots=2, max_len=16))
    ref = entry.offline_decode([5, 6], 4)
    # both queued BEFORE start: one admission round picks both
    doomed = engine.submit([1, 2], max_new_tokens=4)
    survivor = engine.submit([5, 6], max_new_tokens=4)
    faults.configure([{"site": "decode.inject", "action": "raise",
                       "times": 1}])
    engine.start()
    try:
        with pytest.raises(RequestError, match="failed in inject"):
            doomed.result(timeout=120)
        got = [int(t) for t in survivor.result(timeout=120)["tokens"]]
        assert got == ref
    finally:
        engine.shutdown()
        faults.reset()
    assert engine.stats()["tenants"]["default"]["queued"] == 0


def test_half_open_breaker_relaunches_once_while_idle():
    """An open breaker whose cooldown lapses with NO traffic must not
    rebuild the replica on every scheduler tick: one relaunch per
    half-open episode, then the probe STEP decides close/reopen."""
    engine = GenerationEngine(queue_depth=16, breaker_threshold=1,
                              breaker_cooldown_s=0.05)
    entry = engine.register_model(
        lambda: _small_model(name="idleprobe", slots=2, max_len=16))
    faults.configure([{"site": "decode.step", "action": "raise",
                       "times": 1}])
    engine.start()
    try:
        with pytest.raises(RequestError):
            engine.submit([5, 6], max_new_tokens=4).result(timeout=120)
        time.sleep(0.6)  # many loop ticks past cooldown, zero traffic
        st = entry.stats()
        assert st["relaunches"] == 1, st["relaunches"]
        assert st["breaker_probes"] == 1, st["breaker_probes"]
        # the probe step closes the breaker and serves correctly
        out = engine.submit([5, 6], max_new_tokens=4).result(timeout=120)
        assert [int(t) for t in out["tokens"]] == entry.offline_decode(
            [5, 6], 4)
    finally:
        engine.shutdown()
        faults.reset()
    assert entry.stats()["breaker_state"] == "closed"


def test_tenant_admission_quota_rejects_with_measured_backoff():
    engine = GenerationEngine(queue_depth=16, breaker_threshold=0)
    entry = engine.register_model(
        lambda: _small_model(name="quota", slots=2, max_len=8))
    engine.set_tenant("small", max_queued=2)
    # engine NOT started: submissions stay queued
    engine.submit([1, 2], tenant="small", max_new_tokens=2)
    engine.submit([1, 2], tenant="small", max_new_tokens=2)
    with pytest.raises(RejectedError) as exc:
        engine.submit([1, 2], tenant="small", max_new_tokens=2)
    assert "quota" in str(exc.value)
    assert exc.value.retry_after_s > 0.0
    assert entry.metrics.count("rejected_quota") == 1
    assert engine.stats()["tenants"]["small"]["queued"] == 2


def test_model_registry_resolution_and_versioning():
    engine = GenerationEngine(queue_depth=16, breaker_threshold=0)
    engine.register_model(
        lambda: _small_model(name="m", version="1", slots=2, max_len=8))
    e2 = engine.register_model(
        lambda: _small_model(name="m", version="2", slots=2, max_len=8))
    assert engine.models() == [("m", "1"), ("m", "2")]
    assert engine.entry("m") is e2                # latest version wins
    assert engine.entry("m", "2") is e2
    with pytest.raises(RejectedError, match="must name one"):
        engine.submit([1], max_new_tokens=1)      # ambiguous: 2 hosted
    with pytest.raises(RejectedError, match="no model"):
        engine.submit([1], model="ghost", max_new_tokens=1)
    engine.start()
    try:
        out = engine.submit([3, 4], model="m", version="1",
                            max_new_tokens=3).result(timeout=120)
        ref = engine.entry("m", "1").offline_decode([3, 4], 3)
        assert [int(t) for t in out["tokens"]] == ref
    finally:
        engine.shutdown()


def test_submit_validation_rejects_inadmissible_requests(served):
    engine, entry = served
    m = entry.model
    with pytest.raises(RejectedError, match="empty"):
        engine.submit([], max_new_tokens=2)
    with pytest.raises(RejectedError, match="out of range"):
        engine.submit([m.vocab_size], max_new_tokens=2)
    with pytest.raises(RejectedError, match="max_new_tokens"):
        engine.submit([1], max_new_tokens=0)
    with pytest.raises(RejectedError, match="exceeds the KV arena"):
        engine.submit(list(range(1, 16)), max_new_tokens=8)
    with pytest.raises(RejectedError, match="priority"):
        engine.submit([1], priority=99, max_new_tokens=2)


# ---------------------------------------------------------------------------
# satellite: queue drain-rate backoff + expired-vs-rejected split
# ---------------------------------------------------------------------------


class _Row:
    _seq = 0

    def __init__(self, rows=1, priority=Priority.NORMAL, dead=False):
        _Row._seq += 1
        self.id = _Row._seq
        self.rows = rows
        self.priority = priority
        self._dead = dead

    def expired(self, now=None):
        return self._dead


def test_retry_after_tracks_measured_drain_rate():
    q = RequestQueue(max_depth=4)
    for _ in range(4):
        q.put(_Row())
    # cold start: no drain observed yet -> the seed hint
    with pytest.raises(RejectedError) as exc:
        q.put(_Row())
    assert exc.value.retry_after_s == pytest.approx(0.05)
    # drain 3 rows at a measured ~100 rows/s
    for r in list(q.lane(Priority.NORMAL))[:3]:
        time.sleep(0.01)
        q.remove([r])
    est = q.retry_after_estimate(rows=4)
    # 3 rows of overflow at O(100) rows/s: an order-of-magnitude window,
    # not a fixed hint (the EWMA smooths scheduler jitter)
    assert 0.005 <= est <= 1.0
    assert q.stats()["drain_rate_rows_per_s"] > 0
    # caller floor: reported hint is max(measured, caller estimate)
    q.put(_Row(rows=3))
    with pytest.raises(RejectedError) as exc:
        q.put(_Row(), retry_after_s=4.5)
    assert exc.value.retry_after_s == pytest.approx(4.5)


def test_queue_counts_expiry_separately_from_admission_rejects():
    q = RequestQueue(max_depth=2)
    q.put(_Row(dead=True))
    q.put(_Row())
    with pytest.raises(RejectedError):
        q.put(_Row())                      # rejected at admission
    dead = q.expire()
    assert len(dead) == 1                  # expired while queued
    s = q.stats()
    assert s["rejected_at_admission"] == 1
    assert s["expired_in_queue"] == 1
    assert s["depth"] == 1
    assert s["lane_depths"][Priority.NORMAL] == 1


def test_drain_rate_ignores_idle_gaps_between_bursts():
    """Only back-to-back drains of a busy queue are service-rate samples.
    A drain after the queue sat empty spans the idle gap — sampling it
    would converge the EWMA to the ARRIVAL rate, so the first rejection
    of a burst hitting a long-idle queue would back off ~100x too long."""
    q = RequestQueue(max_depth=8)
    for _ in range(4):
        q.put(_Row())
    for r in list(q.lane(Priority.NORMAL)):
        time.sleep(0.005)
        q.remove([r])                  # the last remove empties the queue
    busy = q.stats()["drain_rate_rows_per_s"]
    assert busy > 20.0
    time.sleep(0.3)                    # idle gap: ~3 rows/s if mis-sampled
    q.put(_Row())
    q.remove(list(q.lane(Priority.NORMAL)))
    assert q.stats()["drain_rate_rows_per_s"] == pytest.approx(busy)


def test_pick_rounds_sample_drain_rate_once_per_round():
    """_pick removes one request per call in a tight loop; sampling each
    pick would measure the loop's microsecond gaps (~1e6 rows/s) and
    collapse every retry-after hint to its floor. The round's picks are
    deferred and note_drained() samples them as ONE drain event."""
    engine = GenerationEngine(breaker_threshold=0)
    q = RequestQueue(max_depth=64)
    for i in range(8):
        _queued(q, i, "t")
    for _ in range(4):                 # admission round 1 (4 free slots)
        assert _pick_locked(engine, q) is not None
    q.note_drained()
    time.sleep(0.02)
    for _ in range(4):                 # admission round 2
        assert _pick_locked(engine, q) is not None
    q.note_drained()
    rate = q.stats()["drain_rate_rows_per_s"]
    # 4 rows per ~20ms round is O(200) rows/s; per-pick sampling would
    # have pushed the EWMA toward 1e6
    assert 0 < rate < 5000, rate


def test_finished_generation_delivered_even_if_deadline_lapses_same_step():
    """The device already paid for a COMPLETE generation: 'finished' wins
    over 'expired' on the iteration that lands the final token, matching
    the prefill fast path (which retires without an expiry check).
    Thread-less — the worker is stepped by hand for determinism."""
    engine = GenerationEngine(breaker_threshold=0)
    entry = engine.register_model(
        lambda: _small_model(name="dlwin", slots=1, max_len=16))
    resp = engine.submit([1, 2, 3], max_new_tokens=2, deadline_ms=60000)
    assert entry._admit_free_slots() == 1
    req = entry._slots[0].request
    entry._step()                      # token 1 of 2: mid-flight
    req.deadline = 0.0                 # lapses before the FINAL iteration
    entry._step()                      # token 2: finished AND expired
    got = [int(t) for t in resp.result(timeout=5)["tokens"]]
    assert got == entry.offline_decode([1, 2, 3], 2)


def test_deadline_expires_in_queue_while_slots_are_busy():
    engine = GenerationEngine(queue_depth=16, breaker_threshold=0)
    entry = engine.register_model(
        lambda: _small_model(name="dl", slots=1, max_len=32))
    engine.start()
    try:
        long = engine.submit([1, 2], max_new_tokens=20)   # holds the slot
        doomed = engine.submit([3, 4], max_new_tokens=4, deadline_ms=1.0)
        with pytest.raises(DeadlineExceededError):
            doomed.result(timeout=120)
        long.result(timeout=120)
        assert entry.metrics.count("deadline_missed") >= 1
        assert entry.stats()["queue_expired_in_queue"] >= 1
    finally:
        engine.shutdown()


# ---------------------------------------------------------------------------
# kill a replica mid-decode: breaker re-admits an AOT-warmed replacement
# ---------------------------------------------------------------------------


def test_breaker_relaunches_warm_replica_with_zero_recompiles():
    """An injected decode-step crash loses the in-flight batch (failed
    loudly), opens the breaker, and the cooldown probe relaunches the
    replica — whose three executables ALL come from the in-process
    compile-cache tier (zero new traces), after which generation is
    bit-identical to the offline reference again."""
    engine = GenerationEngine(queue_depth=16, breaker_threshold=1,
                              breaker_cooldown_s=0.05)
    entry = engine.register_model(
        lambda: _small_model(name="kill", slots=2, max_len=16))
    assert entry.compile_sources["trace"] == 3
    ref = entry.offline_decode([5, 6, 7], 6)
    faults.configure([{"site": "decode.step", "action": "raise",
                       "times": 1}])
    engine.start()
    try:
        doomed = engine.submit([5, 6, 7], max_new_tokens=6)
        with pytest.raises(RequestError, match="decode-step failure"):
            doomed.result(timeout=120)
        # the replacement replica serves the SAME request correctly
        out = engine.submit([5, 6, 7], max_new_tokens=6).result(timeout=120)
        assert [int(t) for t in out["tokens"]] == ref
    finally:
        engine.shutdown()
        faults.reset()
    st = entry.stats()
    assert st["step_failures"] == 1
    assert st["relaunches"] == 1
    assert st["breaker_probes"] >= 1
    # zero recompiles: the relaunch re-lowered all three programs from
    # the memory tier; the trace count never moved
    assert entry.compile_sources["trace"] == 3
    assert entry.compile_sources["memory"] >= 3


# ---------------------------------------------------------------------------
# AOT warm start across processes (the cold-replica acceptance gate)
# ---------------------------------------------------------------------------


def _run_worker(cache_dir):
    env = dict(os.environ)
    env.pop("PADDLE_TPU_CACHE_DIR", None)
    env["JAX_PLATFORMS"] = "cpu"
    if cache_dir is not None:
        env["PADDLE_TPU_CACHE_DIR"] = str(cache_dir)
    proc = subprocess.run(
        [sys.executable, WORKER], env=env, capture_output=True,
        text=True, timeout=300,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    return json.loads(proc.stdout.strip().splitlines()[-1])


def test_fresh_process_restores_all_executables_with_zero_compiles(tmp_path):
    """A cold replica with a populated cache dir reaches full decode/
    prefill/inject coverage from the jax.export disk tier: zero traces,
    all three entries disk-sourced, bit-identical generations."""
    cache = tmp_path / "cache"
    cold = _run_worker(cache)
    assert cold["compile_sources"]["trace"] == 3
    warm = _run_worker(cache)
    assert warm["compile_sources"] == {"trace": 0, "disk": 3, "memory": 0}, \
        warm
    assert warm["persistent_hits"] >= 3
    assert warm["persistent_errors"] == 0
    assert warm["tokens"] == cold["tokens"]


# ---------------------------------------------------------------------------
# CLI smoke (tier-1 wiring for tools/bench_serving.py --decode)
# ---------------------------------------------------------------------------


def test_bench_decode_smoke_cli():
    """tools/bench_serving.py --decode --paged --spec --sample --beam
    --smoke is the tier-1 CI hook: open-loop mixed-length workload
    asserting continuous-vs-offline bit-identity for EVERY request in
    EVERY mode (paged block-size sweep, speculative leg, committed-
    sampling replay under two shuffled admission orders, COW beam
    search), zero retraces after warmup, occupancy > 1.5x the
    request-at-a-time baseline, radix dedup > 1 on the share-heavy
    paged leg, speculative steps-per-token < 1, and block-pool
    conservation across beam fork/prune."""
    env = dict(os.environ)
    env["PADDLE_TPU_FORCE_CPU"] = "1"
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "bench_serving.py"),
         "--decode", "--paged", "--spec", "--sample", "--beam", "--smoke"],
        capture_output=True, text=True, timeout=560, env=env,
    )
    assert proc.returncode == 0, proc.stdout[-2000:] + proc.stderr[-2000:]
    assert "DECODE_SMOKE_OK" in proc.stdout
    report = json.loads(proc.stdout.strip().splitlines()[0])
    extra = report["extra"]
    assert extra["retraces_after_warmup"] == 0
    assert extra["offline_mismatches"] == 0
    assert all(s["occupancy_gain"] > 1.5 for s in extra["sweep"])
    paged = extra["paged"]["sweep"]
    assert any(leg["peak_dedup_ratio"] > 1.0 for leg in paged)
    assert all(leg["offline_mismatches"] == 0 for leg in paged)
    assert extra["sample"]["bit_identical"]
    assert extra["sample"]["retraces"] == 0
    assert extra["beam"]["tokens_bit_identical"]
    assert extra["beam"]["conservation_ok"]
    assert extra["beam"]["beam_forks"] > 0
    assert extra["spec"]["steps_per_token"] < 1.0
    assert extra["spec"]["offline_mismatches"] == 0
    assert extra["spec"]["retraces"] == 0


# ---------------------------------------------------------------------------
# HBM budget gate + observability surface
# ---------------------------------------------------------------------------


def test_arena_sized_against_hbm_budget_before_compile():
    tiny = GenerationEngine(breaker_threshold=0, hbm_budget_mb=0.001)
    from paddle_tpu.utils.enforce import EnforceError

    with pytest.raises(EnforceError, match="budget"):
        tiny.register_model(
            lambda: _small_model(name="oom", slots=4, max_len=16))
    roomy = GenerationEngine(breaker_threshold=0, hbm_budget_mb=64)
    entry = roomy.register_model(
        lambda: _small_model(name="fits", slots=2, max_len=8))
    assert entry.model.arena_bytes() < 64 * 2**20


def test_stats_surface_has_decode_and_tenant_series(served):
    engine, entry = served
    out = engine.submit([2, 4, 6], tenant="acme",
                        max_new_tokens=3).result(timeout=120)
    assert len(out["tokens"]) == 3
    st = entry.stats()
    assert st["occupancy"] > 0.0
    # a decode-step quantity: the prefill-derived first token of each
    # admission is counted apart (prefill_tokens), so <= S always holds
    assert 0.0 < st["tokens_per_step"] <= st["slots"]
    assert st["prefill_tokens"] == st["admitted"]
    assert st["compile_sources"]["trace"] == 3
    assert st["arena_mib"] == pytest.approx(
        entry.model.arena_bytes() / 2**20)
    for key in ("latency_p99_s", "queue_wait_p99_s", "decode_step_p99_s",
                "prefill_p99_s", "queue_drain_rate_rows_per_s",
                "queue_rejected_at_admission", "queue_expired_in_queue"):
        assert key in st, key
    assert set(st["queue_lane_depths"]) == {"high", "normal", "low"}
    assert st["tenant_tokens"].get("acme", 0) >= 3
    top = engine.stats()
    assert top["tenants"]["acme"]["in_flight"] == 0
    assert any(h.startswith("shared@") for h in top["hosted"])
    # the per-tenant counters are real registry series (scrapable), not
    # snapshot-only bookkeeping
    from paddle_tpu.observability import metrics as obs_metrics

    text = obs_metrics.registry().to_text()
    assert "serving_tenant_tokens_total" in text
    assert "serving_queue_lane_depth" in text


# ---------------------------------------------------------------------------
# r13: paged arena — block sharing, copy-on-write, exhaustion
# ---------------------------------------------------------------------------


def test_two_requests_share_physical_blocks():
    """Storage dedup, not just prefill dedup: two prompts sharing a
    full-block prefix reference the SAME physical blocks (radix tree
    over chained block hashes) — logical rows exceed physical rows while
    both are live — and still generate bit-identically. Hand-stepped
    (engine not started) so the mid-flight pool state is sampleable."""
    engine = GenerationEngine(queue_depth=16, breaker_threshold=0)
    entry = engine.register_model(lambda: build_decoder_model(
        vocab_size=32, hidden=8, num_layers=2, slots=4, max_len=32,
        block_size=4, name="dedup", version="1"))
    prefix = [7, 3, 9, 2, 11, 5, 8, 1]          # exactly 2 full blocks
    p1, p2 = prefix + [4, 6], prefix + [13]
    refs = [entry.offline_decode(p, 6) for p in (p1, p2)]
    r1 = engine.submit(p1, max_new_tokens=6)
    r2 = engine.submit(p2, max_new_tokens=6)
    assert entry._admit_free_slots() == 2
    bp = entry.block_pool.stats()
    assert bp["dedup_ratio"] > 1.0, bp
    assert bp["rows_logical"] > bp["rows_live"], bp
    assert bp["radix_hits"] >= 2                 # p2 referenced 2 shared blocks
    for _ in range(8):
        entry._step()
    assert [int(t) for t in r1.result(timeout=5)["tokens"]] == refs[0]
    assert [int(t) for t in r2.result(timeout=5)["tokens"]] == refs[1]


def test_cow_on_divergent_append_preserves_bit_identity():
    """Two IDENTICAL prompts share every block including the partial
    tail; the first generated token diverges the sequences, so the
    writer pays a copy-on-write (fresh block + host-row re-inject)
    instead of mutating rows its sharer reads. Both outputs stay
    bit-identical to the offline reference."""
    engine = GenerationEngine(queue_depth=16, breaker_threshold=0)
    entry = engine.register_model(lambda: build_decoder_model(
        vocab_size=32, hidden=8, num_layers=2, slots=4, max_len=32,
        block_size=4, name="cow", version="1"))
    prompt = [7, 3, 9, 2, 11, 5]                 # 1 full block + partial tail
    ref = entry.offline_decode(prompt, 6)
    r1 = engine.submit(prompt, max_new_tokens=6)
    r2 = engine.submit(prompt, max_new_tokens=6)
    assert entry._admit_free_slots() == 2
    bp = entry.block_pool.stats()
    assert bp["dedup_ratio"] > 1.0, bp           # tail shared too
    entry._step()
    assert entry.block_pool.stats()["cow_copies"] >= 1
    for _ in range(8):
        entry._step()
    assert [int(t) for t in r1.result(timeout=5)["tokens"]] == ref
    assert [int(t) for t in r2.result(timeout=5)["tokens"]] == ref
    # the pool never leaks: both retired -> no live blocks
    done = entry.block_pool.stats()
    assert done["blocks_live"] == 0, done


def test_block_pool_exhaustion_fails_loudly_and_recovers():
    """An undersized pool rejects the request that cannot fit — a loud
    request-attributed failure, not an arena loss — and keeps serving
    requests that do fit. Retired registered blocks are evicted on
    demand (LRU) to make room."""
    engine = GenerationEngine(queue_depth=16, breaker_threshold=0)
    entry = engine.register_model(lambda: build_decoder_model(
        vocab_size=32, hidden=8, num_layers=1, slots=2, max_len=16,
        block_size=4, num_blocks=3, name="tightpool", version="1"))
    ref = entry.offline_decode([1, 2], 4)
    engine.start()
    try:
        # 12 rows of pool; a 10-token prompt fills all 3 blocks by its
        # second generated token and the fourth block does not exist
        with pytest.raises(RequestError, match="block pool exhausted"):
            engine.submit(list(range(1, 11)),
                          max_new_tokens=4).result(timeout=120)
        out = engine.submit([1, 2], max_new_tokens=4).result(timeout=120)
        assert [int(t) for t in out["tokens"]] == ref
        # the retired request's registered blocks were cached; admitting
        # fresh prompts evicts them instead of failing
        out2 = engine.submit([3, 4], max_new_tokens=4).result(timeout=120)
        assert [int(t) for t in out2["tokens"]] == \
            entry.offline_decode([3, 4], 4)
    finally:
        engine.shutdown()
    assert entry.metrics.count("blocks_exhausted") >= 1


# ---------------------------------------------------------------------------
# r13: chunked prefill — fairness + bit-identity to unchunked
# ---------------------------------------------------------------------------


def test_chunked_prefill_interleaves_and_matches_unchunked():
    """A long prompt admits through the [1, C] chunk program ONE chunk
    per engine iteration: the in-flight decode slot gains a token EVERY
    iteration of the admission window (never stalls longer than the
    chunk budget), and the chunked generation is bit-identical to the
    offline (unchunked, whole-sequence) reference. Hand-stepped through
    entry._iterate() for a deterministic interleaving record."""
    engine = GenerationEngine(queue_depth=16, breaker_threshold=0)
    entry = engine.register_model(lambda: build_decoder_model(
        vocab_size=32, hidden=8, num_layers=2, slots=2, max_len=32,
        block_size=4, chunk_tokens=5, name="chunkfair", version="1"))
    rng = np.random.RandomState(11)
    long_prompt = [int(t) for t in rng.randint(0, 32, size=17)]
    ref_long = entry.offline_decode(long_prompt, 5)
    ref_short = entry.offline_decode([1, 2], 20)
    short = engine.submit([1, 2], max_new_tokens=20)
    assert entry._admit_free_slots() == 1
    entry._step()                              # short is mid-generation
    lng = engine.submit(long_prompt, max_new_tokens=5)
    progress = []
    for _ in range(40):
        before = len(entry._slots[0].generated)
        if entry._iterate():
            break
        after = (len(entry._slots[0].generated)
                 if entry._slots[0] is not None else before + 1)
        prefilling = any(
            st is not None and st.mode == "prefill" for st in entry._slots)
        progress.append((prefilling, after - before))
        if short.done() and lng.done():
            break
    # fairness: during EVERY iteration the long admission was chunking,
    # the in-flight decode slot still advanced
    chunk_iters = [p for p in progress if p[0]]
    assert len(chunk_iters) >= 2, progress     # 17 tokens / C=5 -> >= 3 chunks
    assert all(delta >= 1 for _p, delta in chunk_iters), progress
    assert [int(t) for t in lng.result(timeout=5)["tokens"]] == ref_long
    assert [int(t) for t in short.result(timeout=5)["tokens"]] == ref_short
    assert entry.metrics.count("chunk_runs") >= 3
    assert entry.metrics.count("chunk_tokens") >= 16


def test_chunked_prefill_skips_radix_shared_chunks():
    """A second long prompt sharing the radix chain chunk-prefills ONLY
    its final chunk (the shared blocks already hold byte-identical rows)
    and still matches the offline reference."""
    engine = GenerationEngine(queue_depth=16, breaker_threshold=0)
    entry = engine.register_model(lambda: build_decoder_model(
        vocab_size=32, hidden=8, num_layers=2, slots=2, max_len=32,
        block_size=4, chunk_tokens=5, name="chunkshare", version="1"))
    rng = np.random.RandomState(12)
    prompt = [int(t) for t in rng.randint(0, 32, size=16)]  # 4 full blocks
    ref = entry.offline_decode(prompt, 4)
    engine.start()
    try:
        out1 = engine.submit(prompt, max_new_tokens=4).result(timeout=120)
        runs_after_first = entry.metrics.count("chunk_runs")
        out2 = engine.submit(prompt, max_new_tokens=4).result(timeout=120)
        runs_after_second = entry.metrics.count("chunk_runs")
    finally:
        engine.shutdown()
    assert [int(t) for t in out1["tokens"]] == ref
    assert [int(t) for t in out2["tokens"]] == ref
    assert runs_after_first >= 4                 # 16 tokens / C=5 -> 4 chunks
    # the re-admission paid ONE chunk (the final-logits chunk), not four
    assert runs_after_second - runs_after_first == 1, (
        runs_after_first, runs_after_second)


@pytest.mark.slow
def test_chunked_prefill_32k_prompt_never_stalls_decode():
    """The satellite's literal claim at production scale: a 32k-token
    prompt admission streams through the chunk program without EVER
    stalling the in-flight decode slot for more than one chunk per
    iteration. (The offline [L, L]-bias reference is unbuildable at 32k
    — 4 GiB per feed — which is exactly why chunked prefill exists; the
    bit-identity of chunk-vs-unchunked is pinned at small scale by
    test_chunked_prefill_interleaves_and_matches_unchunked, and run-to-
    run determinism is asserted here.)"""
    L, C, BS = 32768, 1024, 512
    plen = 32000

    def build():
        return build_decoder_model(
            vocab_size=16, hidden=4, num_layers=1, slots=2, max_len=L,
            block_size=BS, num_blocks=2 * (plen // BS + 4),
            chunk_tokens=C, name="chunk32k", version="1")

    engine = GenerationEngine(queue_depth=8, breaker_threshold=0)
    entry = engine.register_model(build)
    rng = np.random.RandomState(13)
    long_prompt = [int(t) for t in rng.randint(0, 16, size=plen)]
    short = engine.submit([1, 2], max_new_tokens=48)
    assert entry._admit_free_slots() == 1
    entry._step()
    lng = engine.submit(long_prompt, max_new_tokens=4)
    stalls = 0
    toks = []
    while not lng.done():
        st0 = entry._slots[0]
        before = len(st0.generated) if st0 is not None else None
        assert not entry._iterate()
        st0 = entry._slots[0]
        if before is not None and st0 is not None:
            if len(st0.generated) - before < 1:
                stalls += 1
        if short.done() and not any(
                s is not None and s.mode == "prefill"
                for s in entry._slots):
            # short finished before the long prompt landed: keep going
            while not lng.done():
                assert not entry._iterate()
            break
    assert stalls == 0, f"{stalls} iterations stalled the decode slot"
    toks = [int(t) for t in lng.result(timeout=5)["tokens"]]
    assert len(toks) == 4
    assert entry.metrics.count("chunk_runs") >= plen // C
    # run-to-run determinism: a fresh engine reproduces the same bytes
    engine2 = GenerationEngine(queue_depth=8, breaker_threshold=0)
    entry2 = engine2.register_model(build)
    lng2 = engine2.submit(long_prompt, max_new_tokens=4)
    assert entry2._admit_free_slots() == 1
    while not lng2.done():
        assert not entry2._iterate()
    assert [int(t) for t in lng2.result(timeout=5)["tokens"]] == toks


# ---------------------------------------------------------------------------
# r13: speculative decoding — greedy acceptance, bit-identity, steps/token
# ---------------------------------------------------------------------------


def _spec_pair(name, draft_layers=2, **over):
    """Target + draft entries in one engine. Same geometry => the
    deterministic init makes the weights byte-identical (the acceptance
    upper bound); fewer draft layers => a genuinely different model."""
    engine = GenerationEngine(queue_depth=32, breaker_threshold=0)
    tgt = engine.register_model(lambda: build_decoder_model(
        vocab_size=32, hidden=8, num_layers=2, slots=4, max_len=32,
        block_size=4, name=f"{name}_t", version="1", **over))
    engine.register_model(lambda: build_decoder_model(
        vocab_size=32, hidden=8, num_layers=draft_layers, slots=2,
        max_len=32, block_size=4, name=f"{name}_d", version="1", **over))
    return engine, tgt


def test_speculative_decode_bit_identical_any_admission_order():
    """Speculative requests interleaved with normal decode traffic in
    shuffled admission orders: EVERY request's tokens equal the offline
    whole-sequence reference — greedy acceptance makes speculation an
    execution strategy, not a sampling change."""
    engine, tgt = _spec_pair("specmix")
    rng = np.random.RandomState(21)
    prompts = [list(rng.randint(0, 32, size=rng.randint(1, 6)))
               for _ in range(8)]
    max_news = [int(rng.randint(2, 9)) for _ in range(8)]
    refs = [tgt.offline_decode(p, n) for p, n in zip(prompts, max_news)]
    engine.start()
    try:
        for round_seed in (0, 1):
            order = np.random.RandomState(round_seed).permutation(8)
            resps = {}
            for i in order:
                spec = int(i) % 2 == 0
                resps[int(i)] = engine.submit(
                    prompts[i], model="specmix_t",
                    max_new_tokens=max_news[i],
                    draft_model="specmix_d" if spec else None,
                    spec_k=3)
            for i, r in resps.items():
                got = [int(t) for t in r.result(timeout=120)["tokens"]]
                assert got == refs[i], (
                    f"round {round_seed} prompt {i} (spec={i % 2 == 0}): "
                    f"{got} != {refs[i]}")
    finally:
        engine.shutdown()
    st = tgt.stats()
    assert st["spec_emitted_tokens"] > 0
    assert st["spec_target_steps"] < st["spec_emitted_tokens"]


def test_speculative_steps_per_token_below_target():
    """With a byte-identical draft (same geometry, deterministic init)
    acceptance is 1.0 and the measured target-steps-per-emitted-token
    hits the 1/(k+1) floor — and the whole run retraces NOTHING after
    warmup (every mode lives on the already-compiled programs)."""
    engine, tgt = _spec_pair("specsame")
    from paddle_tpu.observability import metrics as obs_metrics

    def jits():
        m = obs_metrics.registry().get("lowering_jit_total")
        return int(m.value) if m is not None else 0

    refs = {}
    prompt = [3, 1, 4, 1, 5]
    refs["a"] = tgt.offline_decode(prompt, 12)
    engine.start()
    j0 = jits()
    try:
        out = engine.submit(prompt, model="specsame_t", max_new_tokens=12,
                            draft_model="specsame_d",
                            spec_k=3).result(timeout=120)
    finally:
        engine.shutdown()
    assert [int(t) for t in out["tokens"]] == refs["a"]
    st = tgt.stats()
    assert st["spec_acceptance_rate"] == 1.0, st["spec_acceptance_rate"]
    assert st["spec_steps_per_token"] <= 0.7, st["spec_steps_per_token"]
    assert st["spec_steps_per_token"] == pytest.approx(
        st["spec_target_steps"] / st["spec_emitted_tokens"])
    assert jits() == j0, "speculative path must not retrace"


def test_speculative_with_distinct_draft_still_bit_identical():
    """A draft that genuinely disagrees with the target (fewer layers,
    different weights) lowers acceptance but can NEVER change the
    output: every emitted token is the target's own greedy argmax."""
    engine, tgt = _spec_pair("specdiff", draft_layers=1)
    prompt = [9, 9, 8, 7]
    ref = tgt.offline_decode(prompt, 10)
    engine.start()
    try:
        out = engine.submit(prompt, model="specdiff_t", max_new_tokens=10,
                            draft_model="specdiff_d",
                            spec_k=3).result(timeout=120)
    finally:
        engine.shutdown()
    assert [int(t) for t in out["tokens"]] == ref
    st = tgt.stats()
    # the ratio is measured, not assumed: it can only beat 1.0 when the
    # draft earns acceptances
    assert st["spec_target_steps"] <= st["spec_emitted_tokens"]


def test_speculative_validation_rejects_bad_drafts():
    engine, tgt = _spec_pair("specval")
    with pytest.raises(RejectedError, match="draft"):
        engine.submit([1], model="specval_t", max_new_tokens=2,
                      draft_model="specval_t")      # draft == target
    with pytest.raises(RejectedError, match="no model"):
        engine.submit([1], model="specval_t", max_new_tokens=2,
                      draft_model="ghost")
    with pytest.raises(RejectedError, match="spec_k"):
        engine.submit([1], model="specval_t", max_new_tokens=2,
                      draft_model="specval_d", spec_k=0)


# ---------------------------------------------------------------------------
# r13 evidence drift gate
# ---------------------------------------------------------------------------


def _load_tool(name):
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_decode_evidence_r13_committed():
    """The committed paged-decode claims must re-derive LIVE: static
    peak-HBM paged-vs-slotted at 8-slot/32k-context (>= 4x), the
    hand-stepped block-dedup admission (ratio > 1, bit-identical,
    token sha256), and the speculative leg (steps-per-token <= 0.7,
    zero retraces, bit-identical) are recomputed in-process and every
    deterministic field compared byte-for-byte. Drift means decode
    behavior changed without regenerating evidence: run
    `python tools/decode_report.py --out DECODE_EVIDENCE_r13.json`."""
    path = os.path.join(REPO, "DECODE_EVIDENCE_r13.json")
    assert os.path.exists(path), "DECODE_EVIDENCE_r13.json missing"
    with open(path) as f:
        committed = json.load(f)
    dr = _load_tool("decode_report")
    fresh = dr.build_evidence()
    dr.check(fresh)                    # live acceptance gates
    dr.check(committed)                # committed claims still qualify
    assert fresh["static_hbm"] == committed["static_hbm"], (
        "static HBM evidence drift:\n"
        f"fresh     {fresh['static_hbm']}\n"
        f"committed {committed['static_hbm']}")
    assert fresh["block_dedup"] == committed["block_dedup"], (
        "block-dedup evidence drift:\n"
        f"fresh     {fresh['block_dedup']}\n"
        f"committed {committed['block_dedup']}")
    assert fresh["speculative"] == committed["speculative"], (
        "speculative evidence drift:\n"
        f"fresh     {fresh['speculative']}\n"
        f"committed {committed['speculative']}")


def test_pool_capacity_check_excludes_blocks_being_shared():
    """Review r13: the admission capacity check must not count cached
    blocks the SAME admission re-references as shared — they stop being
    evictable the moment the commit refs them. Pre-fix this crashed
    mid-commit (None block) and leaked the refcounts forever; post-fix
    it is a clean loud refusal, and the pool still serves afterwards."""
    engine = GenerationEngine(queue_depth=16, breaker_threshold=0)
    entry = engine.register_model(lambda: build_decoder_model(
        vocab_size=32, hidden=8, num_layers=1, slots=2, max_len=24,
        block_size=4, num_blocks=3, name="capcheck", version="1"))
    base = [5, 1, 7, 2, 9, 3, 8, 6]              # exactly 2 full blocks
    engine.start()
    try:
        # leaves both full blocks registered+cached, generated block freed
        out = engine.submit(base, max_new_tokens=2).result(timeout=120)
        assert [int(t) for t in out["tokens"]] == \
            entry.offline_decode(base, 2)
        # 16-token prompt shares those 2 cached blocks and needs 2 MORE:
        # free=1 + evictable=0 (both cached blocks are the shared ones)
        with pytest.raises(RequestError, match="block pool exhausted"):
            engine.submit(base + [4, 4, 4, 4, 2, 2, 2, 2],
                          max_new_tokens=2).result(timeout=120)
        # nothing leaked: the shared-prefix prompt still admits + serves
        out2 = engine.submit(base, max_new_tokens=2).result(timeout=120)
        assert [int(t) for t in out2["tokens"]] == \
            entry.offline_decode(base, 2)
    finally:
        engine.shutdown()
    assert entry.block_pool.stats()["blocks_live"] == 0
