"""Static roofline cost model (ISSUE 16): pre-compile step-time / MFU /
bubble prediction, the hierarchical-collective linter, and the
COST_EVIDENCE_r16 drift gate.

Property contract: analysis/cost.py must assign a FLOP/byte cost to
EVERY op of every example program (unknown_ops empty — a new op entering
the op set without a cost rule fails here), its FLOP totals must agree
with XLA's own ``cost_analysis()`` within a committed tolerance, its
policy-dependent recompute pricing must reorder programs the same way
the static peak-HBM analyzer does, and each linter class must fire on a
synthetic positive control — all before any compile happens.
"""

import importlib.util
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.analysis.cost import (
    MACHINES,
    CostModel,
    analyze_cost,
    check_cost_budgets,
    hierarchical_collective_diagnostics,
    pipeline_bubble_report,
)
from paddle_tpu.analysis.memory import estimate_peak_hbm, remat_hbm_delta
from paddle_tpu.core.ir import Program, program_guard
from paddle_tpu.parallel.env import make_mesh
from paddle_tpu.parallel.spec_layout import SpecLayout

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: static-vs-XLA total-FLOP ratio bound for the property test. Measured
#: spread on the example set is 1.02-1.09 (XLA folds/pads transcendental
#: and reduce work the analytic rules count differently); 1.25 leaves
#: headroom without letting a broken rule (2x = one missed grad) pass.
XLA_FLOPS_TOLERANCE = 1.25


def _discover_examples():
    names = []
    for fn in sorted(os.listdir(os.path.join(REPO, "examples"))):
        path = os.path.join(REPO, "examples", fn)
        if fn.endswith(".py"):
            with open(path) as f:
                if "def build_programs" in f.read():
                    names.append(fn[:-3])
    return tuple(names)


EXAMPLES = _discover_examples()
RUNNABLE_EXAMPLES = tuple(n for n in EXAMPLES if n != "wide_deep")


def _build_example(name):
    from paddle_tpu.passes import (
        apply_deferred_sharded_embedding_rewrite,
        apply_deferred_sparse_rewrite,
    )

    spec = importlib.util.spec_from_file_location(
        f"ca_example_{name}", os.path.join(REPO, "examples", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    main, startup, feed_names, fetch = mod.build_programs()[:4]
    apply_deferred_sparse_rewrite(main)
    apply_deferred_sharded_embedding_rewrite(main)
    return main, startup, list(feed_names), [
        f if isinstance(f, str) else f.name for f in fetch
    ]


def _synthetic_feeds(program, feed_names, batch=16):
    rng = np.random.RandomState(0)
    block = program.global_block()
    out = {}
    for name in feed_names:
        v = block._find_var_recursive(name)
        shape = tuple(batch if d is None or d < 0 else int(d)
                      for d in v.shape)
        dt = str(getattr(v, "dtype", "float32") or "float32")
        if "int" in dt:
            out[name] = np.zeros(shape, dtype=dt)
        else:
            out[name] = rng.uniform(0.0, 1.0, shape).astype(dt)
    return out


# ---------------------------------------------------------------------------
# op coverage: every example op must have a cost rule
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("example", EXAMPLES)
def test_cost_coverage_examples(example):
    main, _startup, feed_names, fetch_names = _build_example(example)
    feed = _synthetic_feeds(main, feed_names)
    rep = analyze_cost(
        main, feed_shapes={k: v.shape for k, v in feed.items()},
        fetch_names=fetch_names,
    )
    assert sorted(rep.unknown_ops) == [], (
        f"{example}: ops without a cost rule — add them to "
        f"analysis/cost.py _FLOP_RULES")
    assert rep.total_flops > 0
    assert rep.step_seconds > 0
    assert 0 < rep.mfu <= 1.0


def test_cost_coverage_bert_and_gpt():
    """The model zoo's structured programs: tiny-BERT pretrain and the
    pipeline_stack GPT — full coverage including the fused/stacked ops."""
    from paddle_tpu.models import bert, gpt_ir

    cfg = bert.BertConfig.tiny()
    main, _s, _f, fetches = bert.build_bert_pretrain(
        cfg, seq_len=24, lr=1e-3, max_predictions_per_seq=20)
    data = bert.synthetic_batch(np.random.RandomState(0), 8, 24, cfg,
                                max_predictions_per_seq=20)
    rep = analyze_cost(
        main, feed_shapes={k: np.asarray(v).shape for k, v in data.items()},
        fetch_names=[fetches[0].name])
    assert sorted(rep.unknown_ops) == []

    gmain, _gs, _gf, gloss, _stack = gpt_ir.build_gpt_ir(
        gpt_ir.GPTIRConfig(), seq_len=16, num_microbatches=4)
    grep = analyze_cost(
        gmain, feed_shapes={"tokens": (8, 16), "labels": (8, 16)},
        fetch_names=[gloss.name], num_stages=4)
    assert sorted(grep.unknown_ops) == []
    assert grep.total_flops > 0


# ---------------------------------------------------------------------------
# FLOPs agree with XLA's cost model
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("example", RUNNABLE_EXAMPLES)
def test_cost_flops_match_xla(example):
    """The analytic FLOP totals must track what XLA's own
    ``compile().cost_analysis()`` reports for the same lowered step."""
    from paddle_tpu.utils import hlo

    main, startup, feed_names, fetch_names = _build_example(example)
    feed = _synthetic_feeds(main, feed_names)
    rep = analyze_cost(
        main, feed_shapes={k: v.shape for k, v in feed.items()},
        fetch_names=fetch_names,
    )
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)
        lowered = hlo.lower_program_step(main, feed, fetch_names,
                                         scope=scope)
    ca = lowered.compile().cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0]
    xla = int(ca.get("flops", 0))
    assert xla > 0
    ratio = max(rep.total_flops, xla) / max(min(rep.total_flops, xla), 1)
    assert ratio <= XLA_FLOPS_TOLERANCE, (
        f"{example}: static {rep.total_flops} vs XLA {xla} "
        f"(ratio {ratio:.4f} > {XLA_FLOPS_TOLERANCE})")


# ---------------------------------------------------------------------------
# remat policies: cost.py and memory.py must agree on the trade
# ---------------------------------------------------------------------------


def _remat_program(policy):
    main, startup = Program(), Program()
    with program_guard(main, startup):
        x = fluid.data("x", shape=[-1, 128])
        y = fluid.data("y", shape=[-1, 1])
        h = x
        ckpts = []
        for _ in range(6):
            h = fluid.layers.fc(h, size=128, act="relu")
            ckpts.append(h)
        pred = fluid.layers.fc(h, size=1)
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
        opt = fluid.optimizer.SGD(learning_rate=0.1)
        if policy:
            opt = fluid.optimizer.RecomputeOptimizer(opt, policy=policy)
            opt._set_checkpoints(ckpts)
        opt.minimize(loss)
    return main, loss


def test_remat_policy_cost_memory_agreement():
    """The policy spectrum must price identically in both analyzers:
    recompute FLOPs full >= dots >= save_all, predicted HBM the inverse
    (full <= dots <= save_all) — and the cost model's byte ordering must
    match the ordering of memory.py's static peak (the two share the
    var-byte resolver, so divergence means a pricing bug, not a shape
    disagreement)."""
    fs = {"x": (512, 128), "y": (512, 1)}
    flops, cost_hbm, peak = {}, {}, {}
    for policy in (None, "full", "dots", "save_all"):
        main, loss = _remat_program(policy)
        rep = analyze_cost(main, feed_shapes=fs, fetch_names=[loss.name])
        assert sorted(rep.unknown_ops) == []
        mem = estimate_peak_hbm(main, feed_shapes=fs,
                                fetch_names=[loss.name])
        flops[policy] = rep.total_flops
        cost_hbm[policy] = rep.total_hbm_bytes
        peak[policy] = mem.peak_total_bytes
    # FLOPs: more recompute = more replay work
    assert flops["full"] > flops["dots"] >= flops["save_all"]
    # every remat policy replays at least the plain backward's work
    assert flops["save_all"] > flops[None]
    # bytes: more saved = more traffic/residency — SAME ordering in both
    assert cost_hbm["full"] < cost_hbm["dots"] < cost_hbm["save_all"]
    assert peak["full"] < peak["dots"] < peak["save_all"]
    # save_all is the no-remat control for peak residency
    assert peak["save_all"] == peak[None]
    # and the pre-compile delta tool reports a real saving for 'full'
    plain, _ = _remat_program(None)
    remat, _ = _remat_program("full")
    delta = remat_hbm_delta(plain, remat, feed_shapes=fs)
    assert delta["saved_bytes"] > 0
    assert delta["policies"] == ["full"]


# ---------------------------------------------------------------------------
# machine model + collective model unit properties
# ---------------------------------------------------------------------------


def test_cost_model_for_mesh_validates():
    from paddle_tpu.utils.enforce import EnforceError

    mesh = make_mesh((2, 4), ("data", "model"))
    cm = CostModel.for_mesh(mesh, machine="tpu-v4-8")
    assert cm.axis_sizes == {"data": 2, "model": 4}
    assert cm.tag("data") == "ici" and cm.tag("model") == "ici"
    with pytest.raises(EnforceError):
        CostModel.for_mesh(mesh, machine="tpu-v4-8",
                           axis_tags={"bogus": "ici"})
    with pytest.raises(EnforceError):
        CostModel.for_mesh(mesh, machine="tpu-v4-8",
                           axis_tags={"data": "wat"})
    with pytest.raises(EnforceError):
        analyze_cost(Program(), machine="not-a-machine")


def test_collective_seconds_two_level():
    """The latency-bandwidth law: a dcn-tagged axis pays dcn latency and
    bandwidth; an all-reduce moves 2(n-1)/n of the payload per axis."""
    mesh = make_mesh((2, 4), ("dcn", "data"))
    cm = CostModel.for_mesh(mesh, machine="tpu-v4-8",
                            axis_tags={"dcn": "dcn", "data": "ici"})
    m = cm.machine
    nbytes = 1 << 20
    got = cm.collective_seconds("all-reduce", nbytes, ("dcn", "data"))
    want = (m.link_lat["dcn"]
            + (2 * (2 - 1) / 2) * nbytes / m.link_bw["dcn"]
            + m.link_lat["ici"]
            + (2 * (4 - 1) / 4) * nbytes / m.link_bw["ici"])
    assert got == pytest.approx(want, rel=1e-12)
    # ici-only all-gather: (n-1)/n, single latency term
    got = cm.collective_seconds("all-gather", nbytes, ("data",))
    assert got == pytest.approx(
        m.link_lat["ici"] + (3 / 4) * nbytes / m.link_bw["ici"],
        rel=1e-12)


def test_machine_table_sane():
    for name, m in MACHINES.items():
        assert m.peak_flops > 0 and m.hbm_bw > 0
        assert m.ridge == pytest.approx(m.peak_flops / m.hbm_bw)
        assert m.link_bw["dcn"] < m.link_bw["ici"], name


# ---------------------------------------------------------------------------
# hierarchical-collective linter: positive + negative controls
# ---------------------------------------------------------------------------


def _mnist_cost_report(axes, axis_tags, input_axes):
    from jax.sharding import PartitionSpec as P

    from paddle_tpu.models import mnist

    main, _startup, feeds, fetches = mnist.build_mnist_train()
    feed_names = [f if isinstance(f, str) else f.name for f in feeds]
    fetch_names = [f if isinstance(f, str) else f.name for f in fetches]
    feed = _synthetic_feeds(main, feed_names)
    return analyze_cost(
        main, mesh=make_mesh((2, 4), axes), axis_tags=axis_tags,
        input_specs={n: P(input_axes) for n in feed_names},
        feed_shapes={k: v.shape for k, v in feed.items()},
        fetch_names=fetch_names,
    )


def test_dcn_allreduce_linter_fires():
    """Positive control: batch split over a dcn-tagged outer axis means
    every grad-sync all-reduce crosses DCN at full payload — the linter
    MUST flag each with the two-level saving."""
    rep = _mnist_cost_report(("dcn", "data"),
                             {"dcn": "dcn", "data": "ici"},
                             ("dcn", "data"))
    diags = hierarchical_collective_diagnostics(rep)
    assert diags, "linter did not fire on the dcn positive control"
    assert all(d.code == "dcn-allreduce-not-hierarchical" for d in diags)
    assert all(d.severity == "error" and d.var for d in diags)
    assert any("save" in d.message for d in diags)


def test_dcn_allreduce_linter_silent_on_ici():
    """Negative control: the same program and mesh, all axes ici —
    hierarchical decomposition buys nothing, the linter stays silent."""
    rep = _mnist_cost_report(("outer", "data"),
                             {"outer": "ici", "data": "ici"},
                             ("outer", "data"))
    assert rep.collectives, "control lost its grad-sync collectives"
    assert hierarchical_collective_diagnostics(rep) == []


def test_cost_budget_gates():
    main, _startup, feed_names, fetch_names = _build_example("fit_a_line")
    feed = _synthetic_feeds(main, feed_names)
    rep = analyze_cost(
        main, feed_shapes={k: v.shape for k, v in feed.items()},
        fetch_names=fetch_names)
    assert check_cost_budgets(rep) == []  # zeros disable every gate
    tight = check_cost_budgets(rep, step_ms=1e-9, min_mfu=1.0)
    codes = {d.code for d in tight}
    assert codes == {"step-time-over-budget", "mfu-under-floor"}


# ---------------------------------------------------------------------------
# pipeline bubble prediction
# ---------------------------------------------------------------------------


def test_pipeline_bubble_gpipe_fraction():
    from paddle_tpu.models import gpt_ir

    gmain, _gs, _gf, _gloss, _stack = gpt_ir.build_gpt_ir(
        gpt_ir.GPTIRConfig(), seq_len=16, num_microbatches=4)
    shapes = {"tokens": (8, 16), "labels": (8, 16)}
    bub = pipeline_bubble_report(gmain, feed_shapes=shapes, num_stages=4)
    assert len(bub) == 1
    ent = bub[0]
    assert ent["schedule"] == "gpipe"
    assert ent["stages"] == 4 and ent["num_microbatches"] == 4
    assert ent["bubble_fraction"] == pytest.approx(3 / 7, abs=1e-6)
    # degenerate stacks cost no bubble
    solo = pipeline_bubble_report(gmain, feed_shapes=shapes, num_stages=1)
    assert solo[0]["bubble_fraction"] == 0.0


# ---------------------------------------------------------------------------
# CLI: lint_program cost subcommand + help/usage contract
# ---------------------------------------------------------------------------


def _lint(*argv):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "lint_program.py"),
         *argv],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=300)


def test_cli_top_level_help():
    r = _lint("--help")
    assert r.returncode == 0
    for sub in ("verify", "shapes", "sharding", "collectives", "memory",
                "cost", "smoke"):
        assert sub in r.stdout, f"--help does not mention '{sub}'"


@pytest.mark.parametrize("sub,flags", [
    ("cost", ("--machine", "--tag", "--budget-step-ms",
              "--budget-collective-kb", "--min-mfu", "--batch-spec",
              "--json")),
    ("sharding", ("--mesh", "--json")),
    ("memory", ("--json",)),
])
def test_cli_subcommand_help_lists_flags(sub, flags):
    r = _lint(sub, "--help")
    assert r.returncode == 0
    for flag in flags:
        assert flag in r.stdout, f"'{sub} --help' missing {flag}"


def test_cli_cost_bad_machine_exits_2():
    r = _lint("cost", "--builtin", "mnist", "--machine", "tpu-v999")
    assert r.returncode == 2, r.stdout + r.stderr
    assert "tpu-v999" in (r.stdout + r.stderr)


def test_cli_cost_clean_and_control():
    r = _lint("cost", "--builtin", "mnist", "--json")
    assert r.returncode == 0, r.stdout + r.stderr
    rep = json.loads(r.stdout.strip().splitlines()[0])
    assert rep["step_seconds"] > 0
    assert rep["unknown_ops"] == []
    # the dcn positive control must exit with findings
    r = _lint("cost", "--builtin", "mnist", "--mesh", "2x4:dcn,data",
              "--tag", "dcn=dcn", "--batch-spec", "dcn,data", "--json")
    assert r.returncode == 1, r.stdout + r.stderr
    rep = json.loads(r.stdout.strip().splitlines()[0])
    assert any(d["code"] == "dcn-allreduce-not-hierarchical"
               for d in rep["diagnostics"])


# ---------------------------------------------------------------------------
# lowering-stage wiring: FLAGS_static_diagnostics=cost
# ---------------------------------------------------------------------------


def test_cost_stage_in_lowering():
    from paddle_tpu.utils.flags import flags

    main, startup, feed_names, fetch_names = _build_example("fit_a_line")
    feed = _synthetic_feeds(main, feed_names, batch=4)
    old = flags.static_diagnostics
    flags.static_diagnostics = "cost"
    try:
        exe = fluid.Executor(fluid.CPUPlace())
        with fluid.scope_guard(fluid.Scope()):
            exe.run(startup)
            out = exe.run(main, feed=feed, fetch_list=fetch_names)
        assert np.all(np.isfinite(np.asarray(out[0])))
    finally:
        flags.static_diagnostics = old


def test_cost_report_smoke_cli():
    """tools/cost_report.py --smoke: the tier-1 drift gate's CLI face —
    recomputes the static half and diffs it against the committed
    evidence in seconds."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "cost_report.py"),
         "--smoke"],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=300)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "smoke OK" in r.stdout


# ---------------------------------------------------------------------------
# COST_EVIDENCE_r16 drift gate (static recompute, r08/r09/r15 style)
# ---------------------------------------------------------------------------


def test_cost_evidence_r16_committed():
    """The committed COST_EVIDENCE_r16.json must be exactly what
    tools/cost_report.py derives TODAY: the static half byte-for-byte,
    the linter control fired, every match verdict 'pass', and a positive
    bubble prediction — evidence that drifts from the code is worse than
    no evidence."""
    tools = os.path.join(REPO, "tools")
    if tools not in sys.path:
        sys.path.insert(0, tools)
    import cost_report

    with open(os.path.join(REPO, "COST_EVIDENCE_r16.json")) as f:
        committed = json.load(f)
    fresh = cost_report.static_sections()
    for tag, sec in fresh.items():
        assert json.dumps(sec, sort_keys=True) == json.dumps(
            committed["arms"][tag]["static"], sort_keys=True), (
            f"COST_EVIDENCE_r16.json static half drifted on arm "
            f"'{tag}' — regenerate with `python tools/cost_report.py "
            f"--out COST_EVIDENCE_r16.json`")
    assert committed["arms"]["dcn_linter_control"]["static"][
        "linter_fired"] > 0
    for tag in cost_report.TOLERANCES:
        m = committed["arms"][tag]["match"]
        assert m["verdict"] == "pass" and \
            m["flops_ratio"] <= m["tolerance"]
    bub = committed["arms"]["pipeline_bubble"]["static"]["pipeline"]
    assert bub and bub[0]["bubble_fraction"] > 0
