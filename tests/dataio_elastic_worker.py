"""Subprocess worker for the ELASTIC crash-resume determinism test.

The elastic sibling of ``dataio_resume_worker.py``: one rank of a
(world)-sized gang trains a tiny linear model over an elastic
DataEngine stream with AutoCheckpoint carrying the iterator position.
Every emitted batch is appended to the log as one JSON line naming the
rank/world/epoch, the batch's epoch-GLOBAL sample positions, a sha256
per sample, and the loss — so the parent test can reconstruct the
committed global stream across a 4 -> 2 world-size change and assert
per-sample exactly-once consumption plus digest conservation against a
world-1 reference run of this same script.

``--kill-at-step N`` SIGKILLs right after step N (mid-epoch, after that
step's checkpoint decision); ``--resume-step S`` pins the elastic
resume to ``ckpt_S`` (the sync step the parent chose), letting the
engine translate the world-4 blob onto this rank's new geometry;
``--max-steps`` stops a surviving rank early (the parent "terminates"
the old gang).
"""

import argparse
import hashlib
import json
import os
import signal
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np

import paddle_tpu as fluid
from paddle_tpu.core.ir import Program, program_guard
from paddle_tpu.dataio import DataEngine, ListSource
from paddle_tpu.incubate.checkpoint import AutoCheckpoint

N_SAMPLES = 96
BATCH = 4


def transform(i, rng):
    x = (np.full(4, float(i), dtype=np.float32) * 0.01
         + np.float32(rng.random() * 1e-3))
    return (x, np.array([x.sum()], dtype=np.float32))


def sample_digest(x, y):
    h = hashlib.sha256()
    h.update(np.ascontiguousarray(x).tobytes())
    h.update(np.ascontiguousarray(y).tobytes())
    return h.hexdigest()


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--ckdir", required=True,
                    help="base dir; this rank uses <ckdir>/rank<r>")
    ap.add_argument("--log", required=True)
    ap.add_argument("--tag", required=True)
    ap.add_argument("--rank", type=int, default=0)
    ap.add_argument("--world", type=int, default=1)
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--seed", type=int, default=11)
    ap.add_argument("--num-workers", type=int, default=0)
    ap.add_argument("--save-interval", type=int, default=2)
    ap.add_argument("--kill-at-step", type=int, default=-1)
    ap.add_argument("--max-steps", type=int, default=-1)
    ap.add_argument("--resume-step", type=int, default=-1)
    args = ap.parse_args()

    source = ListSource(list(range(N_SAMPLES)), seed=args.seed,
                        rank=args.rank, world=args.world)
    engine = DataEngine(source, transform=transform, batch_size=BATCH,
                        drop_last=True, num_workers=args.num_workers,
                        elastic=True)

    main_p, startup = Program(), Program()
    with program_guard(main_p, startup):
        x = fluid.data("x", shape=[-1, 4])
        y = fluid.data("y", shape=[-1, 1])
        pred = fluid.layers.fc(x, size=1)
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
        fluid.optimizer.SGD(learning_rate=0.05).minimize(loss)
        feeder = fluid.DataFeeder([x, y])

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    ck = AutoCheckpoint(exe, main_p,
                        os.path.join(args.ckdir, f"rank{args.rank}"),
                        save_interval_steps=args.save_interval,
                        max_to_keep=16, data_state=engine)
    if args.resume_step >= 0:
        # pinned elastic resume: params from this rank's own chain at
        # the sync step, data blob translated onto (world, rank) by the
        # elastic engine
        step = ck.resume(step=args.resume_step)
    else:
        step = ck.resume()

    with open(args.log, "a") as logf:
        while engine.epoch < args.epochs:
            if args.max_steps >= 0 and step >= args.max_steps:
                break
            advanced = False
            for batch in engine:
                feed = feeder.feed(batch)
                out = exe.run(main_p, feed=feed, fetch_list=[loss])
                c0 = engine.cursor - BATCH
                positions = [engine.base + j * args.world + args.rank
                             for j in range(c0, engine.cursor)]
                digests = [sample_digest(bx, by) for bx, by in batch]
                logf.write(json.dumps({
                    "tag": args.tag, "rank": args.rank,
                    "world": args.world, "step": step,
                    "epoch": engine.epoch, "positions": positions,
                    "digests": digests,
                    "loss": float(out[0][0]).hex(),
                }) + "\n")
                logf.flush()
                advanced = True
                ck.maybe_save(step, blocking=True)
                if step == args.kill_at_step:
                    os.kill(os.getpid(), signal.SIGKILL)
                step += 1
                if args.max_steps >= 0 and step >= args.max_steps:
                    break
            if not advanced:
                break  # empty epoch shard: nothing left for this rank
    ck.close()
    print(f"DONE rank={args.rank} step={step} "
          f"emitted={engine.emitted_batches}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
