"""Op correctness harness, modeled on the reference's OpTest
(reference: python/paddle/fluid/tests/unittests/op_test.py:170) — declare an
op type, numpy inputs and expected outputs; check_output runs the single op
through the real executor; check_grad compares the IR-level backward pass
(append_backward + vjp-synthesized grad ops) against numeric finite
differences (reference: op_test.py:57 get_numeric_gradient).
"""

import numpy as np

import paddle_tpu as fluid
from paddle_tpu.core.ir import Program, program_guard


class OpTest:
    op_type = None
    inputs = {}
    outputs = {}
    attrs = {}

    def _build_program(self):
        prog = Program()
        startup = Program()
        with program_guard(prog, startup):
            block = prog.global_block()
            input_desc = {}
            for slot, arrs in self.inputs.items():
                arrs = arrs if isinstance(arrs, list) else [(slot, arrs)]
                names = []
                for name, arr in arrs:
                    block.create_var(
                        name=name,
                        shape=list(arr.shape),
                        dtype=str(arr.dtype),
                        is_data=True,
                        stop_gradient=False,
                    )
                    names.append(name)
                input_desc[slot] = names
            output_desc = {}
            for slot, outs in self.outputs.items():
                outs = outs if isinstance(outs, list) else [(slot, outs)]
                names = []
                for name, _ in outs:
                    block.create_var(name=name, shape=None, dtype="float32")
                    names.append(name)
                output_desc[slot] = names
            block.append_op(self.op_type, input_desc, output_desc, dict(self.attrs))
        return prog, startup

    def _feed(self):
        feed = {}
        for slot, arrs in self.inputs.items():
            arrs = arrs if isinstance(arrs, list) else [(slot, arrs)]
            for name, arr in arrs:
                feed[name] = arr
        return feed

    def check_output(self, atol=1e-5, rtol=1e-5):
        prog, _ = self._build_program()
        fetch = []
        expected = []
        for slot, outs in self.outputs.items():
            outs = outs if isinstance(outs, list) else [(slot, outs)]
            for name, exp in outs:
                if exp is None:
                    continue
                fetch.append(name)
                expected.append(exp)
        exe = fluid.Executor(fluid.CPUPlace())
        results = exe.run(prog, feed=self._feed(), fetch_list=fetch)
        for name, got, exp in zip(fetch, results, expected):
            np.testing.assert_allclose(
                got,
                exp,
                atol=atol,
                rtol=rtol,
                err_msg=f"{self.op_type} output {name} mismatch",
            )

    def check_grad(
        self,
        inputs_to_check,
        output_name,
        max_relative_error=0.005,
        delta=5e-3,
        no_grad_set=None,
    ):
        """Analytic (IR backward) vs numeric finite-difference gradients of
        mean(output) w.r.t. each input."""
        prog, _ = self._build_program()
        block = prog.global_block()
        from paddle_tpu.core.ir import program_guard as pg

        with pg(prog, Program()):
            out_var = block.vars[output_name]
            loss = fluid.layers.mean(out_var)
            grads = fluid.gradients(
                loss, [block.vars[n] for n in inputs_to_check], no_grad_set=no_grad_set
            )
        exe = fluid.Executor(fluid.CPUPlace())
        feed = self._feed()
        analytic = exe.run(
            prog, feed=feed, fetch_list=[g.name for g in grads]
        )

        def eval_loss(feed_override):
            r = exe.run(prog, feed=feed_override, fetch_list=[loss.name])
            return float(np.asarray(r[0]).reshape(()))

        for name, a_grad in zip(inputs_to_check, analytic):
            base = feed[name].astype(np.float64)
            numeric = np.zeros_like(base)
            flat = base.reshape(-1)
            num_flat = numeric.reshape(-1)
            for i in range(flat.size):
                plus = flat.copy()
                plus[i] += delta
                minus = flat.copy()
                minus[i] -= delta
                f2 = dict(feed)
                f2[name] = plus.reshape(base.shape).astype(feed[name].dtype)
                lp = eval_loss(f2)
                f2[name] = minus.reshape(base.shape).astype(feed[name].dtype)
                lm = eval_loss(f2)
                num_flat[i] = (lp - lm) / (2 * delta)
            a = np.asarray(a_grad, dtype=np.float64)
            denom = np.maximum(np.abs(numeric), np.maximum(np.abs(a), 1e-3))
            rel = np.abs(a - numeric) / denom
            assert rel.max() <= max_relative_error, (
                f"{self.op_type} grad wrt {name}: max rel err {rel.max():.5f} "
                f"(analytic {a.reshape(-1)[:5]}, numeric {numeric.reshape(-1)[:5]})"
            )
