"""Fleet API tests (reference pattern: python/paddle/fluid/tests/unittests/
test_fleet_base.py + test_dist_base.py loss-parity methodology, on the
virtual 8-device CPU mesh)."""

import numpy as np
import pytest

import jax

import paddle_tpu as fluid
from paddle_tpu.core.ir import Program, program_guard
from paddle_tpu.fleet import (
    DistributedStrategy,
    PaddleCloudRoleMaker,
    Role,
    UserDefinedRoleMaker,
    fleet,
)


def test_paddle_cloud_role_maker_env(monkeypatch):
    monkeypatch.setenv("TRAINING_ROLE", "TRAINER")
    monkeypatch.setenv("PADDLE_TRAINER_ID", "2")
    monkeypatch.setenv(
        "PADDLE_TRAINER_ENDPOINTS", "10.0.0.1:6170,10.0.0.2:6170,10.0.0.3:6170"
    )
    rm = PaddleCloudRoleMaker()
    assert rm.is_worker()
    assert not rm.is_server()
    assert rm.worker_index() == 2
    assert rm.worker_num() == 3
    assert not rm.is_first_worker()
    assert rm.get_trainer_endpoints()[1] == "10.0.0.2:6170"


def test_paddle_cloud_role_maker_pserver(monkeypatch):
    monkeypatch.setenv("TRAINING_ROLE", "PSERVER")
    monkeypatch.setenv("PADDLE_PSERVERS_IP_PORT_LIST", "127.0.0.1:7000,127.0.0.1:7001")
    monkeypatch.setenv("PADDLE_CURRENT_ENDPOINT", "127.0.0.1:7001")
    rm = PaddleCloudRoleMaker(is_collective=False)
    assert rm.is_server()
    assert rm.server_index() == 1
    assert rm.server_num() == 2


def test_user_defined_role_maker():
    rm = UserDefinedRoleMaker(
        current_id=0,
        role=Role.WORKER,
        worker_num=4,
        server_endpoints=["127.0.0.1:7164"],
    )
    assert rm.is_first_worker()
    assert rm.worker_num() == 4
    assert rm.server_num() == 1


def _build_model(seed=0):
    x = fluid.data("x", shape=[-1, 8])
    y = fluid.data("y", shape=[-1, 1])
    h = fluid.layers.fc(
        x, size=16, act="relu",
        param_attr=fluid.ParamAttr(initializer=fluid.initializer.Constant(0.05)),
    )
    pred = fluid.layers.fc(
        h, size=1,
        param_attr=fluid.ParamAttr(initializer=fluid.initializer.Constant(0.1)),
    )
    loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
    return loss


def test_collective_fleet_loss_parity(rng, monkeypatch):
    """fleet-compiled distributed run must track the single-device run
    (the reference's TestDistBase assertion, test_dist_base.py:506)."""
    monkeypatch.setenv("PADDLE_TRAINER_ID", "0")
    monkeypatch.setenv("PADDLE_TRAINERS_NUM", "1")
    x = rng.rand(64, 8).astype("float32")
    y = x.sum(axis=1, keepdims=True).astype("float32")

    # single-device reference
    main, startup = Program(), Program()
    with program_guard(main, startup):
        loss = _build_model()
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        ref = [
            float(exe.run(main, feed={"x": x, "y": y}, fetch_list=[loss])[0][0])
            for _ in range(3)
        ]

    # fleet collective run over the 8-device mesh
    main2, startup2 = Program(), Program()
    with program_guard(main2, startup2):
        loss2 = _build_model()
        fleet.init(PaddleCloudRoleMaker())
        strategy = DistributedStrategy()
        dist_opt = fleet.distributed_optimizer(
            fluid.optimizer.SGD(learning_rate=0.1), strategy
        )
        dist_opt.minimize(loss2)
    assert fleet.worker_num() == 1
    with fluid.scope_guard(fluid.Scope()):
        exe.run(fleet.startup_program)
        got = [
            float(
                exe.run(
                    fleet.main_program, feed={"x": x, "y": y}, fetch_list=[loss2]
                )[0][0]
            )
            for _ in range(3)
        ]
    np.testing.assert_allclose(ref, got, rtol=1e-4, atol=1e-5)


def test_collective_fleet_amp_recompute(rng, monkeypatch):
    """Strategy toggles compose: AMP + recompute still train and converge."""
    monkeypatch.setenv("PADDLE_TRAINER_ID", "0")
    monkeypatch.setenv("PADDLE_TRAINERS_NUM", "1")
    x = rng.rand(32, 8).astype("float32")
    y = x.sum(axis=1, keepdims=True).astype("float32")
    main, startup = Program(), Program()
    with program_guard(main, startup):
        xv = fluid.data("x", shape=[-1, 8])
        yv = fluid.data("y", shape=[-1, 1])
        h = fluid.layers.fc(xv, size=16, act="relu")
        h2 = fluid.layers.fc(h, size=16, act="relu")
        pred = fluid.layers.fc(h2, size=1)
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, yv))
        fleet.init(PaddleCloudRoleMaker())
        strategy = DistributedStrategy()
        strategy.recompute = True
        strategy.recompute_checkpoints = [h.name, h2.name]
        dist_opt = fleet.distributed_optimizer(
            fluid.optimizer.SGD(learning_rate=0.1), strategy
        )
        dist_opt.minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(fluid.Scope()):
        exe.run(fleet.startup_program)
        losses = [
            float(
                exe.run(
                    fleet.main_program, feed={"x": x, "y": y}, fetch_list=[loss]
                )[0][0]
            )
            for _ in range(10)
        ]
    assert losses[-1] < losses[0]
    assert np.isfinite(losses).all()
