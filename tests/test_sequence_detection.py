"""Sequence + detection op tests (padded+lengths representation).

Modeled on the reference's test_sequence_pool.py / test_multiclass_nms_op.py
/ test_yolo_box_op.py (reference: python/paddle/fluid/tests/unittests/).
"""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.core.ir import Program, program_guard

from op_test import OpTest


# ---------------------------------------------------------------- sequence
class TestSeqPool(OpTest):
    op_type = "sequence_pool"

    def setup(self, rng, ptype):
        x = rng.randn(3, 5, 4).astype("float32")
        lens = np.array([5, 2, 3], dtype="int64")
        masked = [x[b, : lens[b]] for b in range(3)]
        if ptype == "SUM":
            exp = np.stack([m.sum(0) for m in masked])
        elif ptype == "AVERAGE":
            exp = np.stack([m.mean(0) for m in masked])
        elif ptype == "SQRT":
            exp = np.stack([m.sum(0) / np.sqrt(len(m)) for m in masked])
        elif ptype == "MAX":
            exp = np.stack([m.max(0) for m in masked])
        elif ptype == "LAST":
            exp = np.stack([m[-1] for m in masked])
        else:
            exp = np.stack([m[0] for m in masked])
        self.inputs = {"X": [("x", x)], "Length": [("lens", lens)]}
        self.outputs = {"Out": [("out", exp.astype("float32"))]}
        self.attrs = {"pooltype": ptype}


@pytest.mark.parametrize(
    "ptype", ["SUM", "AVERAGE", "SQRT", "MAX", "LAST", "FIRST"]
)
def test_sequence_pool(rng, ptype):
    t = TestSeqPool()
    t.setup(rng, ptype)
    t.check_output(atol=1e-5)


def test_sequence_pool_grad(rng):
    t = TestSeqPool()
    t.setup(rng, "AVERAGE")
    t.check_grad(["x"], "out", max_relative_error=0.01)


def test_sequence_softmax(rng):
    x = rng.randn(2, 4).astype("float32")
    lens = np.array([4, 2], dtype="int64")
    exp = np.zeros_like(x)
    for b in range(2):
        e = np.exp(x[b, : lens[b]] - x[b, : lens[b]].max())
        exp[b, : lens[b]] = e / e.sum()

    class T(OpTest):
        op_type = "sequence_softmax"
        inputs = {"X": [("x", x)], "Length": [("lens", lens)]}
        outputs = {"Out": [("out", exp)]}

    T().check_output(atol=1e-5)


def test_sequence_reverse(rng):
    x = np.arange(12).reshape(2, 6).astype("float32")
    lens = np.array([4, 6], dtype="int64")
    exp = x.copy()
    exp[0, :4] = x[0, :4][::-1]
    exp[1] = x[1][::-1]

    class T(OpTest):
        op_type = "sequence_reverse"
        inputs = {"X": [("x", x)], "Length": [("lens", lens)]}
        outputs = {"Y": [("y", exp)]}

    T().check_output()


def test_sequence_mask():
    lens = np.array([1, 3, 0], dtype="int64")
    exp = np.array(
        [[1, 0, 0, 0], [1, 1, 1, 0], [0, 0, 0, 0]], dtype="int64"
    )

    class T(OpTest):
        op_type = "sequence_mask"
        inputs = {"X": [("x", lens)]}
        outputs = {"Y": [("y", exp)]}
        attrs = {"maxlen": 4, "out_dtype": "int64"}

    T().check_output()


def test_sequence_expand_as(rng):
    x = rng.randn(2, 3).astype("float32")
    y = np.zeros((2, 4, 3), dtype="float32")
    lens = np.array([2, 4], dtype="int64")
    exp = np.zeros((2, 4, 3), dtype="float32")
    exp[0, :2] = x[0]
    exp[1, :4] = x[1]

    class T(OpTest):
        op_type = "sequence_expand_as"
        inputs = {"X": [("x", x)], "Y": [("y", y)], "Length": [("lens", lens)]}
        outputs = {"Out": [("out", exp)]}

    T().check_output()


def test_sequence_concat(rng):
    a = rng.randn(2, 3).astype("float32")
    b = rng.randn(2, 2).astype("float32")
    la = np.array([2, 3], dtype="int64")
    lb = np.array([1, 2], dtype="int64")
    exp = np.zeros((2, 5), dtype="float32")
    exp[0, :2] = a[0, :2]
    exp[0, 2:3] = b[0, :1]
    exp[1, :3] = a[1, :3]
    exp[1, 3:5] = b[1, :2]

    class T(OpTest):
        op_type = "sequence_concat"
        inputs = {
            "X": [("a", a), ("b", b)],
            "Length": [("la", la), ("lb", lb)],
        }
        outputs = {
            "Out": [("out", exp)],
            "OutLength": [("outlen", np.array([3, 5], dtype="int64"))],
        }

    T().check_output()


def test_sequence_erase():
    x = np.array([[1, 2, 3, 2, 5], [2, 2, 7, 0, 0]], dtype="int64")
    lens = np.array([5, 3], dtype="int64")
    exp = np.array([[1, 3, 5, 0, 0], [7, 0, 0, 0, 0]], dtype="int64")

    class T(OpTest):
        op_type = "sequence_erase"
        inputs = {"X": [("x", x)], "Length": [("lens", lens)]}
        outputs = {
            "Out": [("out", exp)],
            "OutLength": [("outlen", np.array([3, 1], dtype="int64"))],
        }
        attrs = {"tokens": [2]}

    T().check_output()


def test_sequence_enumerate():
    x = np.array([[1, 2, 3, 4]], dtype="int64")
    exp = np.array([[[1, 2], [2, 3], [3, 4], [4, 0]]], dtype="int64")

    class T(OpTest):
        op_type = "sequence_enumerate"
        inputs = {"X": [("x", x)]}
        outputs = {"Out": [("out", exp)]}
        attrs = {"win_size": 2, "pad_value": 0}

    T().check_output()


def test_sequence_conv_layer(rng):
    B, S, F = 2, 6, 3
    main, startup = Program(), Program()
    with program_guard(main, startup):
        x = fluid.data("x", shape=[-1, S, F])
        lens = fluid.data("lens", shape=[-1], dtype="int64")
        y = fluid.layers.sequence_conv(x, num_filters=5, filter_size=3,
                                       length=lens, bias_attr=False)
        loss = fluid.layers.mean(y)
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    out = exe.run(
        main,
        feed={"x": rng.randn(B, S, F).astype("float32"),
              "lens": np.array([6, 3], dtype="int64")},
        fetch_list=[y],
    )[0]
    assert out.shape == (B, S, 5)
    assert np.allclose(out[1, 3:], 0)  # masked tail stays zero


# ---------------------------------------------------------------- detection
def _iou_np(a, b):
    xx1 = max(a[0], b[0]); yy1 = max(a[1], b[1])
    xx2 = min(a[2], b[2]); yy2 = min(a[3], b[3])
    inter = max(0.0, xx2 - xx1) * max(0.0, yy2 - yy1)
    ua = (a[2] - a[0]) * (a[3] - a[1]) + (b[2] - b[0]) * (b[3] - b[1]) - inter
    return inter / ua if ua > 0 else 0.0


def test_iou_similarity(rng):
    x = np.abs(rng.rand(4, 4)).astype("float32")
    x[:, 2:] = x[:, :2] + np.abs(rng.rand(4, 2)) + 0.1
    y = np.abs(rng.rand(3, 4)).astype("float32")
    y[:, 2:] = y[:, :2] + np.abs(rng.rand(3, 2)) + 0.1
    exp = np.zeros((4, 3), dtype="float32")
    for i in range(4):
        for j in range(3):
            exp[i, j] = _iou_np(x[i], y[j])

    class T(OpTest):
        op_type = "iou_similarity"
        inputs = {"X": [("x", x)], "Y": [("y", y)]}
        outputs = {"Out": [("out", exp)]}

    T().check_output(atol=1e-5)


def test_multiclass_nms_suppresses_overlaps(rng):
    # two heavily-overlapping boxes + one distinct: expect 2 detections
    boxes = np.array(
        [[[0, 0, 10, 10], [0.5, 0.5, 10.5, 10.5], [20, 20, 30, 30]]],
        dtype="float32",
    )
    scores = np.array([[[0.0, 0.0, 0.0], [0.9, 0.8, 0.7]]], dtype="float32")
    main, startup = Program(), Program()
    with program_guard(main, startup):
        b = fluid.data("b", shape=[1, 3, 4])
        s = fluid.data("s", shape=[1, 2, 3])
        out, num = fluid.layers.multiclass_nms(
            b, s, score_threshold=0.1, nms_threshold=0.5, keep_top_k=5
        )
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    o, n = exe.run(main, feed={"b": boxes, "s": scores},
                   fetch_list=[out, num])
    assert int(n[0]) == 2
    kept = o[0][o[0][:, 0] >= 0]
    assert kept.shape[0] == 2
    # the highest-scoring overlapping box (score .9) and the distinct one
    assert np.isclose(sorted(kept[:, 1])[-1], 0.9)
    assert {tuple(r[2:4]) for r in kept} == {(0.0, 0.0), (20.0, 20.0)}


def test_yolo_box_shapes(rng):
    B, A, C, H, W = 2, 3, 4, 5, 5
    x = rng.randn(B, A * (5 + C), H, W).astype("float32")
    img = np.array([[320, 320], [160, 320]], dtype="int64")
    main, startup = Program(), Program()
    with program_guard(main, startup):
        xv = fluid.data("x", shape=[B, A * (5 + C), H, W])
        iv = fluid.data("img", shape=[B, 2], dtype="int64")
        boxes, scores = fluid.layers.yolo_box(
            xv, iv, anchors=[10, 13, 16, 30, 33, 23], class_num=C,
            conf_thresh=0.0, downsample_ratio=32,
        )
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    b, s = exe.run(main, feed={"x": x, "img": img}, fetch_list=[boxes, scores])
    assert b.shape == (B, A * H * W, 4)
    assert s.shape == (B, A * H * W, C)
    assert (b[0][:, 0] >= 0).all() and (b[0][:, 2] < 320).all()


def test_prior_box_layer(rng):
    main, startup = Program(), Program()
    with program_guard(main, startup):
        feat = fluid.data("feat", shape=[1, 8, 4, 4])
        img = fluid.data("img", shape=[1, 3, 32, 32])
        boxes, variances = fluid.layers.prior_box(
            feat, img, min_sizes=[8.0], aspect_ratios=[1.0, 2.0], flip=True,
            clip=True,
        )
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    b, v = exe.run(
        main,
        feed={"feat": rng.randn(1, 8, 4, 4).astype("float32"),
              "img": rng.randn(1, 3, 32, 32).astype("float32")},
        fetch_list=[boxes, variances],
    )
    assert b.shape == (4, 4, 3, 4)  # 1 min_size * (1 + 2 flipped ars)
    assert (b >= 0).all() and (b <= 1).all()
    assert v.shape == b.shape


def test_box_coder_roundtrip(rng):
    """decode(encode(x)) == x for center-size coding."""
    prior = np.array([[0, 0, 10, 10], [5, 5, 20, 25]], dtype="float32")
    target = np.array([[1, 1, 8, 8], [6, 7, 18, 22]], dtype="float32")
    main, startup = Program(), Program()
    with program_guard(main, startup):
        p = fluid.data("p", shape=[2, 4])
        t = fluid.data("t", shape=[2, 4])
        enc = fluid.layers.box_coder(p, [1.0, 1.0, 1.0, 1.0], t,
                                     code_type="encode_center_size")
        dec = fluid.layers.box_coder(p, [1.0, 1.0, 1.0, 1.0], enc,
                                     code_type="decode_center_size")
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    d = exe.run(main, feed={"p": prior, "t": target}, fetch_list=[dec])[0]
    # decode output is [N, M, 4]; the diagonal should reproduce targets
    np.testing.assert_allclose(
        np.stack([d[0, 0], d[1, 1]]), target, rtol=1e-4, atol=1e-4
    )


def test_bipartite_match():
    dist = np.array(
        [[0.9, 0.1, 0.3], [0.2, 0.8, 0.4]], dtype="float32"
    )
    main, startup = Program(), Program()
    with program_guard(main, startup):
        d = fluid.data("d", shape=[2, 3])
        ids, md = fluid.layers.bipartite_match(d)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    i, m = exe.run(main, feed={"d": dist}, fetch_list=[ids, md])
    assert i[0][0] == 0 and i[0][1] == 1 and i[0][2] == -1
    np.testing.assert_allclose(m[0][:2], [0.9, 0.8], rtol=1e-5)


def test_anchor_generator_layer(rng):
    main, startup = Program(), Program()
    with program_guard(main, startup):
        feat = fluid.data("feat", shape=[1, 8, 3, 3])
        anchors, variances = fluid.layers.anchor_generator(
            feat, anchor_sizes=[32.0], aspect_ratios=[1.0],
            stride=[16.0, 16.0],
        )
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    a, v = exe.run(
        main, feed={"feat": rng.randn(1, 8, 3, 3).astype("float32")},
        fetch_list=[anchors, variances],
    )
    assert a.shape == (3, 3, 1, 4)
    # center cell anchor: center at (1.5*16)=24, square of size 32
    np.testing.assert_allclose(a[1, 1, 0], [8, 8, 40, 40], atol=1e-4)


# ---------------------------------------------------------------------------
# r4 tranche: sequence_expand/reshape/scatter, lod_reset, chunk_eval,
# beam_search (+decode)
# ---------------------------------------------------------------------------


def _lower(op, ins, attrs=None):
    import jax.numpy as jnp

    from paddle_tpu.core.registry import get_op_def

    ins = {k: [jnp.asarray(v) for v in vs] for k, vs in ins.items()}
    return get_op_def(op).lower(ins, attrs or {})


def test_sequence_expand_reshape_scatter(rng):
    x = rng.randn(3, 4).astype("float32")
    yl = np.array([2, 0, 3], "int64")
    out = _lower("sequence_expand", {"X": [x], "YLength": [yl]},
                 {"max_repeat": 4})["Out"][0]
    out = np.asarray(out)
    assert out.shape == (3, 4, 4)
    np.testing.assert_allclose(out[0, :2], np.stack([x[0]] * 2))
    np.testing.assert_allclose(out[0, 2:], 0.0)
    np.testing.assert_allclose(out[1], 0.0)
    np.testing.assert_allclose(out[2, :3], np.stack([x[2]] * 3))

    x2 = rng.randn(2, 4, 6).astype("float32")
    r = np.asarray(_lower("sequence_reshape", {"X": [x2]},
                          {"new_dim": 8})["Out"][0])
    assert r.shape == (2, 3, 8)
    np.testing.assert_allclose(r.reshape(2, -1), x2.reshape(2, -1))

    base = np.zeros((2, 6), "float32")
    ids = np.array([[1, 1, 4], [0, 5, 5]], "int64")
    upd = np.ones((2, 3), "float32")
    sc = np.asarray(_lower("sequence_scatter",
                           {"X": [base], "Ids": [ids], "Updates": [upd]}
                           )["Out"][0])
    np.testing.assert_allclose(sc[0], [0, 2, 0, 0, 1, 0])
    np.testing.assert_allclose(sc[1], [1, 0, 0, 0, 0, 2])


def test_chunk_eval_iob(rng):
    # tags: type*2 + pos, pos 0=B 1=I; two types
    # label:  B0 I0 | B1 | B0      inference: B0 I0 | B0 | B0
    lab = np.array([[0, 1, 2, 0]], "int64")
    inf = np.array([[0, 1, 0, 0]], "int64")
    outs = _lower("chunk_eval", {"Inference": [inf], "Label": [lab]},
                  {"chunk_scheme": "IOB", "num_chunk_types": 2})
    n_inf = int(np.asarray(outs["NumInferChunks"][0])[0])
    n_lab = int(np.asarray(outs["NumLabelChunks"][0])[0])
    n_cor = int(np.asarray(outs["NumCorrectChunks"][0])[0])
    assert (n_inf, n_lab) == (3, 3)
    # correct: the first chunk [0,1] type0 and the last single B0 chunk
    assert n_cor == 2
    p = float(np.asarray(outs["Precision"][0])[0])
    np.testing.assert_allclose(p, 2 / 3, rtol=1e-5)


def test_beam_search_step_and_decode(rng):
    """3-step beam search over a tiny hand-built distribution: the decoded
    best lane must equal the brute-force best path."""
    import jax.numpy as jnp

    B, W, K, V = 1, 2, 3, 10
    end_id = 0
    rs = np.random.RandomState(0)
    pre_ids = np.full((B, W), 5, "int64")
    pre_scores = np.array([[0.0, -0.5]], "float32")
    all_ids, all_parents = [], []
    for t in range(3):
        ids = rs.randint(1, V, (B, W, K)).astype("int64")
        scores = np.log(rs.rand(B, W, K).astype("float32") + 1e-3)
        outs = _lower("beam_search",
                      {"pre_ids": [pre_ids], "pre_scores": [pre_scores],
                       "ids": [ids], "scores": [scores]},
                      {"end_id": end_id, "beam_size": W,
                       "is_accumulated": False})
        pre_ids = np.asarray(outs["selected_ids"][0]).astype("int64")
        pre_scores = np.asarray(outs["selected_scores"][0])
        all_ids.append(pre_ids.copy())
        all_parents.append(np.asarray(outs["parent_idx"][0]).copy())
    dec = _lower("beam_search_decode",
                 {"Ids": [np.stack(all_ids)],
                  "Parents": [np.stack(all_parents)],
                  "Scores": [pre_scores]})
    sent = np.asarray(dec["SentenceIds"][0])  # [B, W, T]
    assert sent.shape == (1, 2, 3)
    # lane w's last token must be the step-3 selection for lane w
    np.testing.assert_array_equal(sent[0, :, -1], all_ids[-1][0])
    # walking parents manually reproduces lane 0's history
    lane = 0
    toks = []
    for t in (2, 1, 0):
        toks.append(all_ids[t][0, lane])
        lane = all_parents[t][0, lane]
    np.testing.assert_array_equal(sent[0, 0], toks[::-1])


def test_beam_search_ended_beam_keeps_end_token():
    import numpy as np

    pre_ids = np.array([[0, 7]], "int64")   # beam 0 already ended
    pre_scores = np.array([[5.0, 0.1]], "float32")
    ids = np.array([[[1, 2], [3, 4]]], "int64")
    scores = np.log(np.array([[[0.9, 0.05], [0.6, 0.3]]], "float32"))
    outs = _lower("beam_search",
                  {"pre_ids": [pre_ids], "pre_scores": [pre_scores],
                   "ids": [ids], "scores": [scores]}, {"end_id": 0})
    sel = np.asarray(outs["selected_ids"][0])[0]
    sc = np.asarray(outs["selected_scores"][0])[0]
    # the ended beam survives as end_id with its carried score 5.0
    assert 0 in sel.tolist()
    assert abs(sc[sel.tolist().index(0)] - 5.0) < 1e-6


def test_chunk_eval_outside_tag_not_a_chunk():
    """Code-review r4: the O tag (id num_chunk_types*2) must not start or
    extend chunks — B0 I0 O O is exactly ONE chunk."""
    lab = np.array([[0, 1, 2, 2]], "int64")
    outs = _lower("chunk_eval", {"Inference": [lab], "Label": [lab]},
                  {"chunk_scheme": "IOB", "num_chunk_types": 1})
    assert int(np.asarray(outs["NumLabelChunks"][0])[0]) == 1
    assert int(np.asarray(outs["NumCorrectChunks"][0])[0]) == 1
    # chunk broken by O: B0 O B0 -> two chunks
    lab2 = np.array([[0, 2, 0]], "int64")
    outs2 = _lower("chunk_eval", {"Inference": [lab2], "Label": [lab2]},
                   {"chunk_scheme": "IOB", "num_chunk_types": 1})
    assert int(np.asarray(outs2["NumLabelChunks"][0])[0]) == 2


def test_sequence_expand_keeps_int_dtype(rng):
    ids = rng.randint(0, 9, (2, 3)).astype("int64")
    yl = np.array([2, 1], "int64")
    out = _lower("sequence_expand", {"X": [ids], "YLength": [yl]},
                 {"max_repeat": 3})["Out"][0]
    assert "int" in str(out.dtype), out.dtype


def test_beam_search_accumulated_scores():
    """is_accumulated=True (reference default): scores already carry the
    history, pre_scores must NOT be re-added for live beams."""
    pre_ids = np.array([[3, 7]], "int64")
    pre_scores = np.array([[100.0, 200.0]], "float32")
    ids = np.array([[[1, 2], [3, 4]]], "int64")
    scores = np.array([[[-1.0, -2.0], [-3.0, -4.0]]], "float32")
    outs = _lower("beam_search",
                  {"pre_ids": [pre_ids], "pre_scores": [pre_scores],
                   "ids": [ids], "scores": [scores]},
                  {"end_id": 0, "is_accumulated": True})
    sc = np.asarray(outs["selected_scores"][0])[0]
    np.testing.assert_allclose(sorted(sc, reverse=True), [-1.0, -2.0])


def test_chunk_eval_excluded_type_terminates(rng):
    """Code-review r4: an excluded-type chunk still terminates the
    preceding chunk (boundaries use raw starts)."""
    # label: B0 B1 ; inference: B0 O — B0 spans [0,1) in BOTH
    lab = np.array([[0, 2]], "int64")
    inf = np.array([[0, 4]], "int64")  # O = nct*2 = 4
    outs = _lower("chunk_eval", {"Inference": [inf], "Label": [lab]},
                  {"chunk_scheme": "IOB", "num_chunk_types": 2,
                   "excluded_chunk_types": [1]})
    assert int(np.asarray(outs["NumLabelChunks"][0])[0]) == 1
    assert int(np.asarray(outs["NumInferChunks"][0])[0]) == 1
    assert int(np.asarray(outs["NumCorrectChunks"][0])[0]) == 1


def test_sequence_expand_clamps_outlength(rng):
    x = rng.randn(2, 3).astype("float32")
    yl = np.array([12, 1], "int64")
    outs = _lower("sequence_expand", {"X": [x], "YLength": [yl]},
                  {"max_repeat": 8})
    np.testing.assert_array_equal(np.asarray(outs["OutLength"][0]), [8, 1])
    from paddle_tpu.utils.enforce import EnforceError
    import pytest as _pytest
    with _pytest.raises(EnforceError, match="YLength"):
        _lower("sequence_expand", {"X": [x]}, {})
