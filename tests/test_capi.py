"""C ABI + Go binding tests: compile the C smoke host against libcapi.so,
run it out-of-process (the embedded interpreter boots fresh), and compare
its output against the in-process predictor. reference test pattern:
paddle/fluid/inference/capi/ tests + go/demo."""

import os
import shutil
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.core.ir import Program, program_guard

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _save_model(tmpdir, rng):
    main, startup = Program(), Program()
    with program_guard(main, startup):
        x = fluid.data("x", [-1, 6])
        h = fluid.layers.fc(x, 8, act="relu")
        pred = fluid.layers.fc(h, 2)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        model_dir = os.path.join(str(tmpdir), "model")
        fluid.io.save_inference_model(model_dir, ["x"], [pred], exe,
                                      main_program=main)
    return model_dir, pred.name


@pytest.fixture(scope="module")
def capi_lib():
    from paddle_tpu.inference.capi import build_capi

    try:
        return build_capi()
    except Exception as e:  # no toolchain/libpython — skip, don't fail
        pytest.skip(f"cannot build libcapi: {e}")


def test_capi_smoke_from_c_host(tmp_path, rng, capi_lib):
    model_dir, _ = _save_model(tmp_path, rng)
    capi_dir = os.path.dirname(capi_lib)
    exe_path = os.path.join(str(tmp_path), "capi_smoke")
    build = subprocess.run(
        ["g++", os.path.join(REPO, "tests", "capi_smoke.c"),
         f"-I{capi_dir}", f"-L{capi_dir}", "-lcapi",
         f"-Wl,-rpath,{capi_dir}", "-o", exe_path],
        capture_output=True, text=True, timeout=120,
    )
    assert build.returncode == 0, build.stderr

    batch, feat = 3, 6
    env = dict(os.environ)
    env["PADDLE_TPU_FORCE_CPU"] = "1"  # embedded interpreter must not probe TPU
    proc = subprocess.run(
        [exe_path, model_dir, str(batch), str(feat)],
        capture_output=True, text=True, timeout=600, env=env,
    )
    assert proc.returncode == 0, f"stdout={proc.stdout}\nstderr={proc.stderr}"
    lines = dict(
        l.split("=", 1) if "=" in l else (l.split(":")[0], l.split(":", 1)[1])
        for l in proc.stdout.strip().splitlines()
    )
    assert lines["inputs"].split()[0] == "1"
    assert lines["clone_match"] == "1"
    got = np.array([float(v) for v in lines["values"].split()], "float32")

    # in-process predictor on the same input must agree exactly
    from paddle_tpu import inference

    config = inference.Config(model_dir)
    config.disable_tpu()
    p = inference.create_predictor(config)
    x = (np.arange(batch * feat) % 7).astype("float32") * 0.25 - 0.5
    ref = p.run([x.reshape(batch, feat)])[0].reshape(-1)
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)


def test_go_binding_symbols_resolve():
    """Toolchain-free ABI drift check (tools/check_go_binding.py): every
    C.<symbol> the Go binding references must exist in paddle_tpu_capi.h.
    The actual `go build` remains environment-gated below (no Go toolchain
    and no network in this image — recorded per round in ROUND*_NOTES)."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "check_go_binding.py")],
        capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_go_binding_compiles(tmp_path, rng, capi_lib):
    if shutil.which("go") is None:
        pytest.skip("no Go toolchain in this image")
    model_dir, _ = _save_model(tmp_path, rng)
    godir = os.path.join(REPO, "go", "paddle")
    env = dict(os.environ)
    env["PADDLE_TPU_FORCE_CPU"] = "1"
    env["CGO_CFLAGS"] = f"-I{os.path.dirname(capi_lib)}"
    env["CGO_LDFLAGS"] = (
        f"-L{os.path.dirname(capi_lib)} -lcapi "
        f"-Wl,-rpath,{os.path.dirname(capi_lib)}"
    )
    proc = subprocess.run(
        ["go", "run", os.path.join(REPO, "go", "demo", "main.go"),
         model_dir],
        capture_output=True, text=True, timeout=600, env=env, cwd=godir,
    )
    assert proc.returncode == 0, f"stdout={proc.stdout}\nstderr={proc.stderr}"
    assert "ok" in proc.stdout


def _save_train_model(tmpdir):
    main, startup = Program(), Program()
    with program_guard(main, startup):
        x = fluid.data("x", [-1, 2])
        y = fluid.data("y", [-1, 1])
        pred = fluid.layers.fc(x, 1, num_flatten_dims=1)
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
        fluid.optimizer.SGD(learning_rate=0.2).minimize(loss)
    model_dir = os.path.join(str(tmpdir), "train_model")
    fluid.io.save_train_model(model_dir, main, startup, loss=loss)
    return model_dir


def test_capi_train_from_c_host(tmp_path, capi_lib):
    """C host trains the exported program end to end (reference:
    paddle/fluid/train/demo/demo_trainer.cc flow over the C ABI)."""
    model_dir = _save_train_model(tmp_path)
    capi_dir = os.path.dirname(capi_lib)
    exe_path = os.path.join(str(tmp_path), "capi_train_smoke")
    build = subprocess.run(
        ["g++", os.path.join(REPO, "tests", "capi_train_smoke.c"),
         f"-I{capi_dir}", f"-L{capi_dir}", "-lcapi",
         f"-Wl,-rpath,{capi_dir}", "-o", exe_path],
        capture_output=True, text=True, timeout=120,
    )
    assert build.returncode == 0, build.stderr
    save_dir = os.path.join(str(tmp_path), "saved")
    env = dict(os.environ)
    env["PADDLE_TPU_FORCE_CPU"] = "1"
    r = subprocess.run(
        [exe_path, model_dir, "20", save_dir],
        capture_output=True, text=True, timeout=300, env=env,
    )
    assert r.returncode == 0, (r.stdout, r.stderr[-2000:])
    assert "CAPI_TRAIN_OK" in r.stdout
    # persistables were saved (param + optimizer state files exist)
    assert os.path.isdir(save_dir) and len(os.listdir(save_dir)) >= 2


def test_trainer_bridge_warm_start(tmp_path, rng):
    """Python-level bridge check: save_train_model with executor saves
    persistables; a new trainer warm-starts from them instead of re-running
    random init (the reference train API's LoadPersistables flow)."""
    from paddle_tpu.inference import capi_bridge as bridge

    main, startup = Program(), Program()
    with program_guard(main, startup):
        x = fluid.data("x", [-1, 2])
        y = fluid.data("y", [-1, 1])
        pred = fluid.layers.fc(x, 1, num_flatten_dims=1,
                               param_attr=fluid.ParamAttr(name="tw"))
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    model_dir = os.path.join(str(tmp_path), "warm")
    with fluid.scope_guard(scope):
        exe.run(startup)
        scope.set("tw", np.full((2, 1), 0.25, dtype="float32"))
        fluid.io.save_train_model(model_dir, main, startup, loss=loss,
                                  executor=exe)

    tr = bridge.new_trainer(model_dir, use_tpu=False)
    got = np.asarray(tr.scope.find_var("tw"))
    np.testing.assert_allclose(got, 0.25)
    # and it can step
    feed_x = rng.randn(4, 2).astype("float32")
    feed_y = rng.randn(4, 1).astype("float32")
    bridge.trainer_set_input(tr, "x", 0, (4, 2), memoryview(feed_x.tobytes()))
    bridge.trainer_set_input(tr, "y", 0, (4, 1), memoryview(feed_y.tobytes()))
    dt, shape, raw = bridge.trainer_run(tr, "")
    assert np.isfinite(np.frombuffer(raw, "float32")).all()
