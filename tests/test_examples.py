"""The examples/ book scripts stay runnable (slow: each is an end-to-end
train + serve flow in a subprocess)."""

import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.parametrize("name", ["fit_a_line", "recognize_digits",
                                  "serve_transformer", "serve_generation",
                                  "wide_deep"])
def test_example_runs(name):
    env = dict(os.environ)
    env["PADDLE_TPU_FORCE_CPU"] = "1"
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "examples", f"{name}.py")],
        env=env, capture_output=True, text=True, timeout=560,
    )
    assert proc.returncode == 0, proc.stdout[-1500:] + proc.stderr[-1500:]
