"""Data pipeline tests: reader decorators, DataFeeder, DataLoader, and the
native C++ dataset backend (reference patterns: python/paddle/reader/tests,
python/paddle/fluid/tests/unittests/test_dataset.py)."""

import os

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.core.ir import Program, program_guard
from paddle_tpu.dataset import DatasetFactory, _NativeFeed, _PyFeed, _SlotSpec
from paddle_tpu.reader import decorator as dec


# ---------------------------------------------------------------------------
# decorators
# ---------------------------------------------------------------------------


def _counter_reader(n):
    def reader():
        yield from range(n)

    return reader


def test_decorator_batch_and_shuffle():
    r = dec.batch(_counter_reader(10), 3)
    batches = list(r())
    assert [len(b) for b in batches] == [3, 3, 3, 1]
    r = dec.batch(_counter_reader(10), 3, drop_last=True)
    assert [len(b) for b in r()] == [3, 3, 3]
    r = dec.shuffle(_counter_reader(20), buf_size=50)
    assert sorted(r()) == list(range(20))


def test_decorator_compose_chain_cache_firstn():
    c = dec.compose(_counter_reader(3), _counter_reader(3))
    assert list(c()) == [(0, 0), (1, 1), (2, 2)]
    ch = dec.chain(_counter_reader(2), _counter_reader(2))
    assert list(ch()) == [0, 1, 0, 1]
    cached = dec.cache(_counter_reader(4))
    assert list(cached()) == list(cached())
    assert list(dec.firstn(_counter_reader(100), 5)()) == [0, 1, 2, 3, 4]


def test_decorator_buffered_and_xmap():
    buf = dec.buffered(_counter_reader(50), size=4)
    assert list(buf()) == list(range(50))
    xm = dec.xmap_readers(lambda x: x * 2, _counter_reader(30), 4, 8, order=True)
    assert list(xm()) == [2 * i for i in range(30)]
    xm2 = dec.xmap_readers(lambda x: x * 2, _counter_reader(30), 4, 8)
    assert sorted(xm2()) == [2 * i for i in range(30)]


def test_xmap_propagates_errors():
    def bad(x):
        raise ValueError("boom")

    xm = dec.xmap_readers(bad, _counter_reader(3), 2, 4)
    with pytest.raises(ValueError):
        list(xm())


# ---------------------------------------------------------------------------
# DataFeeder + DataLoader
# ---------------------------------------------------------------------------


def test_data_feeder_shapes():
    main = Program()
    with program_guard(main, Program()):
        img = fluid.data("img", shape=[-1, 2, 2])
        label = fluid.data("label", shape=[-1, 1], dtype="int64")
        feeder = fluid.DataFeeder([img, label])
    feed = feeder.feed([(np.ones(4), 3), (np.zeros(4), 1)])
    assert feed["img"].shape == (2, 2, 2)
    assert feed["img"].dtype == np.float32
    assert feed["label"].shape == (2, 1)
    assert feed["label"].dtype == np.int64


def test_dataloader_trains(rng):
    main, startup = Program(), Program()
    with program_guard(main, startup):
        x = fluid.data("x", shape=[-1, 4])
        y = fluid.data("y", shape=[-1, 1])
        pred = fluid.layers.fc(x, size=1)
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
        fluid.optimizer.SGD(learning_rate=0.05).minimize(loss)
        loader = fluid.DataLoader.from_generator(feed_list=[x, y], capacity=4)

    def sample_gen():
        for i in range(64):
            xv = rng.rand(4).astype("float32")
            yield xv, np.array([xv.sum()], dtype="float32")

    loader.set_sample_generator(sample_gen, batch_size=16)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    losses = []
    for epoch in range(8):
        for feed in loader:
            out = exe.run(main, feed=feed, fetch_list=[loss])
            losses.append(float(out[0][0]))
    assert losses[-1] < losses[0]


# ---------------------------------------------------------------------------
# native dataset backend
# ---------------------------------------------------------------------------

MULTISLOT = """2 11 12 1 0.5 3 7 8 9
2 21 22 1 1.5 1 4
2 31 32 1 2.5 2 5 6
"""
SLOTS = [
    _SlotSpec("ids", "int64", 2),
    _SlotSpec("w", "float32", 1),
    _SlotSpec("seq", "int64", -1),
]


@pytest.mark.parametrize("feed_cls", [_NativeFeed, _PyFeed])
def test_feed_backends_parse_and_batch(feed_cls):
    feed = feed_cls(SLOTS)
    feed.load_buffer(MULTISLOT)
    assert feed.size() == 3
    feed.begin_pass(2, False)
    assert feed.next_batch() == 2
    ids, _ = feed.batch_arrays(0)
    np.testing.assert_array_equal(ids, [[11, 12], [21, 22]])
    w, _ = feed.batch_arrays(1)
    np.testing.assert_allclose(w, [[0.5], [1.5]])
    seq, lens = feed.batch_arrays(2)
    np.testing.assert_array_equal(lens, [3, 1])
    np.testing.assert_array_equal(seq, [[7, 8, 9], [4, 0, 0]])
    assert feed.next_batch() == 1
    assert feed.next_batch() == 0


def test_native_matches_python_on_files(tmp_path, rng):
    """Backend parity: the C++ parser/batcher must agree with the Python
    fallback on multi-file input."""
    paths = []
    for f in range(3):
        lines = []
        for i in range(17):
            n = rng.randint(1, 5)
            vals = " ".join(str(rng.randint(0, 100)) for _ in range(n))
            lines.append(f"2 {f} {i} 1 {rng.rand():.4f} {n} {vals}")
        p = tmp_path / f"part-{f}.txt"
        p.write_text("\n".join(lines) + "\n")
        paths.append(str(p))

    outs = []
    for cls in (_NativeFeed, _PyFeed):
        feed = cls(SLOTS)
        feed.load_files(paths, nthreads=3)
        feed.begin_pass(8, False)
        got = []
        while feed.next_batch() > 0:
            got.append([feed.batch_arrays(i) for i in range(len(SLOTS))])
        outs.append(got)
    assert len(outs[0]) == len(outs[1])
    for b0, b1 in zip(*outs):
        for (a0, l0), (a1, l1) in zip(b0, b1):
            np.testing.assert_array_equal(a0, a1)
            np.testing.assert_array_equal(l0, l1)


def test_inmemory_dataset_end_to_end(tmp_path, rng):
    """InMemoryDataset + train_from_dataset (reference:
    test_dataset.py + executor train_from_dataset)."""
    lines = []
    for i in range(64):
        x = rng.rand(4)
        y = x.sum()
        lines.append(
            "4 " + " ".join(f"{v:.5f}" for v in x) + f" 1 {y:.5f}"
        )
    p = tmp_path / "data.txt"
    p.write_text("\n".join(lines) + "\n")

    main, startup = Program(), Program()
    with program_guard(main, startup):
        x = fluid.data("x", shape=[-1, 4])
        y = fluid.data("y", shape=[-1, 1])
        pred = fluid.layers.fc(x, size=1)
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
        fluid.optimizer.SGD(learning_rate=0.05).minimize(loss)

    ds = DatasetFactory().create_dataset("InMemoryDataset")
    ds.set_batch_size(16)
    ds.set_thread(2)
    ds.set_use_var([x, y])
    ds.set_filelist([str(p)])
    ds.load_into_memory()
    assert ds.get_memory_data_size() == 64
    ds.local_shuffle(seed=1)

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    first = exe.run(main, feed=next(ds._iter_batches()), fetch_list=[loss])
    for _ in range(10):
        out = exe.train_from_dataset(
            main, ds, fetch_list=[loss], print_period=10**9
        )
    assert float(out[0][0]) < float(first[0][0])
