"""Unified lowering + content-addressed persistent compile cache.

The tentpole contract (ROADMAP open item 5 / ISSUE 6): one lowering
entrypoint for Executor / CompiledProgram / Predictor, a process-wide
memory tier shared by all of them, and an on-disk tier keyed by a
content-addressed program fingerprint so a SECOND PROCESS running the
same program compiles zero times — and a corrupt/truncated entry falls
back to a retrace silently with bit-identical results, never a crash or
a wrong answer.
"""

import json
import os
import subprocess
import sys
import threading

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.core import compile_cache
from paddle_tpu.core.ir import Program, program_guard

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "compile_cache_worker.py")


def _run_worker(cache_dir=None, hidden=16):
    env = dict(os.environ)
    env.pop("PADDLE_TPU_CACHE_DIR", None)
    env["JAX_PLATFORMS"] = "cpu"
    if cache_dir is not None:
        env["PADDLE_TPU_CACHE_DIR"] = str(cache_dir)
    proc = subprocess.run(
        [sys.executable, WORKER, "--hidden", str(hidden)],
        env=env, capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    return json.loads(proc.stdout.strip().splitlines()[-1])


def _entries(cache_dir):
    return sorted(
        f for f in os.listdir(cache_dir) if f.endswith(".ptcc")
    )


# ---------------------------------------------------------------------------
# cross-process reuse (the acceptance gate)
# ---------------------------------------------------------------------------


def test_cross_process_warm_start(tmp_path):
    """Second fresh process on the same program: ZERO traces, zero
    compile-histogram observations, and bit-identical losses — with and
    without the cache enabled."""
    cache = tmp_path / "cache"
    baseline = _run_worker(cache_dir=None)
    assert baseline["traces"] > 0  # startup + train step

    cold = _run_worker(cache_dir=cache)
    assert cold["traces"] == baseline["traces"]
    assert _entries(cache), "populate run wrote no cache entries"
    # cache enabled vs disabled must not change a single bit
    assert cold["losses"] == baseline["losses"]

    warm = _run_worker(cache_dir=cache)
    assert warm["traces"] == 0, f"warm process retraced: {warm}"
    assert warm["compile_observations"] == 0
    assert warm["persistent_hits"] > 0
    assert warm["losses"] == baseline["losses"]


def test_poisoned_cache_entries_fall_back_to_retrace(tmp_path):
    """Flip bytes in one entry, truncate another: the run must silently
    retrace (correct, bit-identical losses), count the corruption, and
    quarantine the bad entries as *.corrupt."""
    cache = tmp_path / "cache"
    baseline = _run_worker(cache_dir=cache)
    entries = _entries(cache)
    assert len(entries) >= 2  # startup + main step

    # bit-rot in the payload of the first entry
    p0 = cache / entries[0]
    raw = bytearray(p0.read_bytes())
    raw[-8] ^= 0xFF
    p0.write_bytes(bytes(raw))
    # torn write on the second
    p1 = cache / entries[1]
    p1.write_bytes(p1.read_bytes()[: max(8, len(p1.read_bytes()) // 3)])

    poisoned = _run_worker(cache_dir=cache)
    assert poisoned["losses"] == baseline["losses"]
    assert poisoned["traces"] == baseline["traces"]  # full retrace
    assert poisoned["persistent_errors"] >= 2
    corrupt = [f for f in os.listdir(cache) if f.endswith(".corrupt")]
    assert len(corrupt) >= 2, "bad entries were not quarantined"

    # the retrace re-populated the cache: a fourth process is warm again
    warm = _run_worker(cache_dir=cache)
    assert warm["traces"] == 0
    assert warm["losses"] == baseline["losses"]


def test_garbage_file_in_cache_dir_is_ignored(tmp_path):
    cache = tmp_path / "cache"
    _run_worker(cache_dir=cache)
    for name in _entries(cache):
        (cache / name).write_bytes(b"not a cache entry at all")
    out = _run_worker(cache_dir=cache)
    assert out["traces"] > 0  # fell back
    assert out["persistent_errors"] >= 1


# ---------------------------------------------------------------------------
# fingerprint semantics
# ---------------------------------------------------------------------------


def _tiny_program(hidden=4):
    # reset auto-naming so two builds of the same code are textually
    # identical — the position a fresh process is always in
    from paddle_tpu.utils import unique_name

    with unique_name.guard():
        main, startup = Program(), Program()
        with program_guard(main, startup):
            x = fluid.data("x", shape=[-1, 4])
            loss = fluid.layers.mean(fluid.layers.fc(x, size=hidden))
    return main


def test_fingerprint_stability_and_sensitivity():
    feed_sig = (("x", (2, 4), "float32"),)
    p1, p2 = _tiny_program(), _tiny_program()
    fp = compile_cache.program_fingerprint(p1, feed_sig, ["loss"])
    # identical CONTENT -> identical fingerprint, even for distinct objects
    assert fp == compile_cache.program_fingerprint(p2, feed_sig, ["loss"])
    # any input that can change the compiled artifact must change it
    assert fp != compile_cache.program_fingerprint(
        _tiny_program(hidden=8), feed_sig, ["loss"])
    assert fp != compile_cache.program_fingerprint(
        p1, (("x", (4, 4), "float32"),), ["loss"])
    assert fp != compile_cache.program_fingerprint(
        p1, feed_sig, ["loss", "other"])
    assert fp != compile_cache.program_fingerprint(
        p1, feed_sig, ["loss"], donate=False)
    assert fp != compile_cache.program_fingerprint(
        p1, feed_sig, ["loss"], extra=("mb", 4))
    assert fp != compile_cache.program_fingerprint(
        p1, feed_sig, ["loss"], scope_sig=(("w", (4, 4), "float32"),))


def test_fingerprint_covers_jax_version_and_backend(monkeypatch):
    """A jax upgrade or backend switch must invalidate persisted entries
    (stale modules fall back to retrace, never a wrong answer)."""
    import jax

    feed_sig = (("x", (2, 4), "float32"),)
    p = _tiny_program()
    fp = compile_cache.program_fingerprint(p, feed_sig, ["loss"])
    monkeypatch.setattr(jax, "__version__", "999.0.0")
    assert fp != compile_cache.program_fingerprint(p, feed_sig, ["loss"])


def test_flag_changes_miss_cleanly():
    from paddle_tpu.utils.flags import flags

    feed_sig = (("x", (2, 4), "float32"),)
    p = _tiny_program()
    fp = compile_cache.program_fingerprint(p, feed_sig, ["loss"])
    old = flags.rng_impl
    try:
        flags.rng_impl = "rbg"
        assert fp != compile_cache.program_fingerprint(p, feed_sig, ["loss"])
    finally:
        flags.rng_impl = old


# ---------------------------------------------------------------------------
# in-process sharing + single-flight
# ---------------------------------------------------------------------------


def test_memory_tier_shared_across_executors(rng):
    """Two Executor objects (fresh per-executor cheap caches) lowering the
    same program content share ONE trace through the process-wide tier."""
    from paddle_tpu.core.executor import _CACHE_MISSES

    compile_cache.clear_memory_cache()
    main, startup = Program(), Program()
    with program_guard(main, startup):
        x = fluid.data("x", shape=[-1, 6])
        loss = fluid.layers.mean(fluid.layers.fc(x, size=3))
    feed = {"x": rng.rand(2, 6).astype("float32")}

    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe1 = fluid.Executor(fluid.CPUPlace())
        exe1.run(startup)
        m0 = _CACHE_MISSES.value
        r1 = exe1.run(main, feed=feed, fetch_list=[loss])
        assert _CACHE_MISSES.value == m0 + 1
        exe2 = fluid.Executor(fluid.CPUPlace())
        r2 = exe2.run(main, feed=feed, fetch_list=[loss])
        # exe2 never traced: served from the shared memory tier
        assert _CACHE_MISSES.value == m0 + 1
        np.testing.assert_array_equal(np.asarray(r1[0]), np.asarray(r2[0]))


def test_single_flight_dedupes_concurrent_predictor_compiles(tmp_path, rng):
    """The documented lock-free race (N clones x same signature -> N
    duplicate compiles under replica warmup) is gone: concurrent requests
    for one signature share a single in-flight compile."""
    from paddle_tpu import inference
    from paddle_tpu.observability import metrics as obs_metrics

    compile_cache.clear_memory_cache()
    main, startup = Program(), Program()
    with program_guard(main, startup):
        x = fluid.data("x", shape=[-1, 9])
        h = fluid.layers.fc(x, size=7, act="relu")
        pred = fluid.layers.fc(h, size=2)
    exe = fluid.Executor(fluid.CPUPlace())
    model_dir = str(tmp_path / "model")
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        fluid.io.save_inference_model(model_dir, ["x"], [pred], exe,
                                      main_program=main)

    config = inference.Config(model_dir)
    config.disable_tpu()
    predictor = inference.create_predictor(config)
    clones = [predictor.clone() for _ in range(7)]

    def compile_count():
        h = obs_metrics.registry().get("predictor_compile_seconds")
        return h.count if h is not None else 0

    before = compile_count()
    barrier = threading.Barrier(len(clones) + 1)
    errors = []
    outs = []

    def worker(p):
        try:
            barrier.wait(timeout=30)
            outs.append(p.run_batch({"x": np.ones((3, 9), "float32")}))
        except Exception as e:  # pragma: no cover - surfaced by assert
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(c,)) for c in clones]
    for t in threads:
        t.start()
    barrier.wait(timeout=30)
    for t in threads:
        t.join(timeout=120)
    assert not errors, errors
    after = compile_count()
    assert after - before == 1, \
        f"expected exactly 1 compile for 7 concurrent requests, got " \
        f"{after - before}"
    # threads that reach the local-cache check after the leader stores
    # the bucket legitimately record hits, so misses is a range, not 7
    stats = predictor.cache_stats()
    assert 1 <= stats["misses"] <= 7
    ref = outs[0]
    for o in outs[1:]:
        for k in ref:
            np.testing.assert_array_equal(ref[k], o[k])


def test_predictor_and_executor_share_one_lowering(tmp_path, rng):
    """Train and serve share one cache: a Predictor bucket lowered first
    is reused when an identical program/feed signature arrives (both
    route through core/lowering.py — the grep gate in the acceptance
    criteria is behavioral here)."""
    from paddle_tpu.core import lowering

    compile_cache.clear_memory_cache()
    main, startup = Program(), Program()
    with program_guard(main, startup):
        x = fluid.data("x", shape=[-1, 5])
        out = fluid.layers.fc(x, size=2)
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        feed_sig = (("x", (2, 5), "float32"),)
        e1, s1 = lowering.lower_step(main, scope, feed_sig, [out.name],
                                     donate=False, label="predictor")
        e2, s2 = lowering.lower_step(main, scope, feed_sig, [out.name],
                                     donate=False, label="predictor")
        assert s1 == "trace" and s2 == "memory"
        assert e1 is e2


# ---------------------------------------------------------------------------
# mandatory pre-lowering verification
# ---------------------------------------------------------------------------


def test_verifier_gates_lowering(rng):
    """A malformed program (use-before-def) must fail verification BEFORE
    tracing — naming the diagnostic, not crashing inside a lowering
    rule."""
    from paddle_tpu.utils.enforce import EnforceError

    main, startup = Program(), Program()
    with program_guard(main, startup):
        x = fluid.data("x", shape=[-1, 4])
        loss = fluid.layers.mean(fluid.layers.fc(x, size=2))
    block = main.global_block()
    block.create_var(name="never_written", shape=[4], dtype="float32")
    block.append_op(
        "elementwise_add",
        inputs={"X": ["never_written"], "Y": ["never_written"]},
        outputs={"Out": ["never_written_out"]},
    )
    block.create_var(name="never_written_out", shape=[4], dtype="float32")
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        with pytest.raises(EnforceError, match="verification"):
            exe.run(main, feed={"x": rng.rand(2, 4).astype("float32")},
                    fetch_list=[loss])


# ---------------------------------------------------------------------------
# cold-start bench CLI (tier-1 wiring, like bench_input/trace_view)
# ---------------------------------------------------------------------------


def test_bench_cold_start_smoke_cli():
    """tools/bench_cold_start.py --smoke: warm processes report zero
    traces/compiles and bit-identical first losses."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "bench_cold_start.py"),
         "--smoke", "--hidden", "24"],
        capture_output=True, text=True, timeout=560,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "SMOKE OK" in proc.stdout
