"""Fault-tolerant training runtime (paddle_tpu/resilience + hardened
checkpoint/launcher/serving paths).

Covers: the deterministic fault-injection harness itself; the shared
retry policy; crash-consistent checkpoints (CRC manifests, fallback
chain walking, *.corrupt quarantine, close() error surfacing); the
fail-fast gang launcher and the supervised-restart loop (crash, budget
exhaustion, heartbeat-declared hangs); the robust reader decorator; the
lookup-path retry; the serving replica circuit breaker; and the
tools/chaos_train.py --smoke CI hook (worker kill + checkpoint
corruption -> supervised auto-resume, bit-identical to an uninterrupted
reference).
"""

import json
import os
import subprocess
import sys
import textwrap
import time

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu.core.ir import Program, program_guard
from paddle_tpu.incubate.checkpoint import (
    AutoCheckpoint,
    CheckpointCorruptError,
    load_checkpoint,
    newest_valid_checkpoint,
    verify_checkpoint,
)
from paddle_tpu.resilience import (
    FaultInjector,
    GangFailedError,
    GangSupervisor,
    InjectedFault,
    RetryPolicy,
    TransientFault,
    corrupt_file,
    faults,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


# ---------------------------------------------------------------------------
# fault injection harness
# ---------------------------------------------------------------------------


def test_fault_rule_matching_at_call_and_times():
    inj = FaultInjector([
        {"site": "a", "action": "raise", "at_call": 2},
        {"site": "b", "action": "raise", "times": 2},
    ])
    inj.fire("a")  # call 1: no fire
    with pytest.raises(TransientFault):
        inj.fire("a")
    inj.fire("a")  # times=1 exhausted
    for _ in range(2):
        with pytest.raises(TransientFault):
            inj.fire("b")
    inj.fire("b")  # times=2 exhausted
    assert inj.rule_stats()["a:0"]["fired"] == 1
    assert inj.rule_stats()["b:1"]["fired"] == 2


def test_fault_at_call_counts_calls_consumed_by_earlier_rules():
    """A firing rule must not hide the call from later rules' at_call
    counters — the written schedule IS the replayed timeline."""
    inj = FaultInjector([
        {"site": "s", "action": "raise", "times": 1},
        {"site": "s", "action": "raise", "at_call": 2, "exc": "fault"},
    ])
    with pytest.raises(TransientFault):
        inj.fire("s")  # call 1: rule 0 fires
    with pytest.raises(InjectedFault):
        inj.fire("s")  # call 2: rule 1 fires ON THE SECOND CALL


def test_fault_rule_step_rank_and_exc_class():
    inj = FaultInjector([
        {"site": "train.step", "at_step": 3, "rank": 1, "exc": "fault"},
    ])
    inj.fire("train.step", step=3, rank=0)  # wrong rank
    inj.fire("train.step", step=2, rank=1)  # wrong step
    with pytest.raises(InjectedFault):
        inj.fire("train.step", step=3, rank=1)


def test_fault_env_configuration(monkeypatch):
    monkeypatch.setenv(
        faults.FAULTS_ENV,
        json.dumps([{"site": "x", "action": "raise"}]),
    )
    faults.reset()  # force env re-parse
    with pytest.raises(TransientFault):
        faults.fire("x")
    faults.fire("x")  # one-shot
    faults.reset()
    monkeypatch.delenv(faults.FAULTS_ENV)
    faults.fire("x")  # inert again


def test_fault_state_dir_survives_process_restart(tmp_path):
    """The cross-process one-shot marker: a 'restarted' injector replaying
    the same schedule must not re-fire."""
    spec = [{"site": "s", "action": "raise", "id": "once"}]
    inj1 = FaultInjector(spec, state_dir=str(tmp_path))
    with pytest.raises(TransientFault):
        inj1.fire("s")
    inj2 = FaultInjector(spec, state_dir=str(tmp_path))  # "restart"
    inj2.fire("s")  # marker present: no fire
    assert inj2.rule_stats()["once"]["fired"] == 0


def test_fault_state_dir_only_pins_one_shot_rules(tmp_path):
    """Multi-fire rules (times>1 or unlimited) must KEEP firing across a
    process restart — only times=1 rules record cross-process markers."""
    spec = [{"site": "s", "action": "raise", "times": -1, "id": "forever"}]
    inj1 = FaultInjector(spec, state_dir=str(tmp_path))
    for _ in range(3):
        with pytest.raises(TransientFault):
            inj1.fire("s")
    inj2 = FaultInjector(spec, state_dir=str(tmp_path))  # "restart"
    with pytest.raises(TransientFault):
        inj2.fire("s")


def test_verify_checkpoint_bad_meta_types_quarantine(tmp_path):
    """meta.json that is valid JSON but has a non-numeric step must be
    treated as corruption (walk-back), not crash resume()."""
    _saved_checkpoints(tmp_path, steps=2)
    with open(tmp_path / "ckpt_1" / "meta.json", "w") as f:
        json.dump({"step": None}, f)
    with pytest.raises(CheckpointCorruptError, match="bad meta.json"):
        verify_checkpoint(str(tmp_path / "ckpt_1"))
    assert newest_valid_checkpoint(str(tmp_path), quarantine=False) == "ckpt_0"


def test_corrupt_file_flip_and_truncate(tmp_path):
    p = str(tmp_path / "f.bin")
    payload = bytes(range(256)) * 4
    with open(p, "wb") as f:
        f.write(payload)
    n = corrupt_file(p, mode="flip", nbytes=8)
    assert n == 8
    with open(p, "rb") as f:
        got = f.read()
    assert len(got) == len(payload) and got != payload
    corrupt_file(p, mode="truncate")
    assert os.path.getsize(p) == len(payload) // 2


# ---------------------------------------------------------------------------
# retry policy
# ---------------------------------------------------------------------------


def test_retry_succeeds_after_transients():
    sleeps = []
    p = RetryPolicy(max_attempts=4, base_delay_s=0.01, jitter=0.0,
                    sleep=sleeps.append)
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise TransientFault("blip")
        return "ok"

    assert p.call(flaky) == "ok"
    assert len(calls) == 3
    assert sleeps == [0.01, 0.02]  # capped exponential, jitter off


def test_retry_does_not_mask_real_errors():
    p = RetryPolicy(max_attempts=5, base_delay_s=0.0, sleep=lambda s: None)
    calls = []

    def broken():
        calls.append(1)
        raise ValueError("logic bug")

    with pytest.raises(ValueError):
        p.call(broken)
    assert len(calls) == 1  # not retried


def test_retry_deadline_and_exhaustion():
    p = RetryPolicy(max_attempts=3, base_delay_s=0.0, sleep=lambda s: None)
    with pytest.raises(TransientFault):
        p.call(lambda: (_ for _ in ()).throw(TransientFault("always")))
    # deadline: a huge backoff would blow the budget -> raise immediately
    p2 = RetryPolicy(max_attempts=10, base_delay_s=100.0, deadline_s=0.5,
                     sleep=lambda s: None)
    calls = []

    def fail():
        calls.append(1)
        raise TransientFault("x")

    with pytest.raises(TransientFault):
        p2.call(fail)
    assert len(calls) == 1


def test_retry_jitter_deterministic_with_seed():
    a = RetryPolicy(max_attempts=5, base_delay_s=0.1, seed=42,
                    sleep=lambda s: None)
    b = RetryPolicy(max_attempts=5, base_delay_s=0.1, seed=42,
                    sleep=lambda s: None)
    assert [a.delay(i) for i in range(1, 5)] == [
        b.delay(i) for i in range(1, 5)
    ]


def test_retry_on_retry_hook_runs_between_attempts():
    seen = []
    p = RetryPolicy(max_attempts=3, base_delay_s=0.0, sleep=lambda s: None)
    state = {"n": 0}

    def fn():
        state["n"] += 1
        if state["n"] < 3:
            raise ConnectionError("reset")
        return state["n"]

    assert p.call(fn, on_retry=lambda e, a: seen.append(a)) == 3
    assert seen == [1, 2]


# ---------------------------------------------------------------------------
# robust reader decorator (fluid.io.robust)
# ---------------------------------------------------------------------------


class _FlakyIter:
    """Class-based (resumable) iterator: record 3 raises, others yield."""

    def __init__(self, n):
        self.i = -1
        self.n = n

    def __iter__(self):
        return self

    def __next__(self):
        self.i += 1
        if self.i >= self.n:
            raise StopIteration
        if self.i == 3:
            raise IOError("bad record")
        return self.i


def test_robust_reader_skips_bad_record_resumable_iterator():
    reader = fluid.io.robust(lambda: _FlakyIter(6), max_skips=2)
    assert list(reader()) == [0, 1, 2, 4, 5]


def test_robust_reader_restarts_dead_generator():
    attempts = []

    def gen_reader():
        attempts.append(1)
        for i in range(6):
            if i == 3 and len(attempts) == 1:  # first pass only
                raise IOError("torn read")
            yield i

    reader = fluid.io.robust(gen_reader, max_skips=2, max_restarts=2)
    # the generator dies at record 3; the decorator restarts the reader
    # and fast-forwards past the 3 consumed + 1 bad record
    assert list(reader()) == [0, 1, 2, 4, 5]
    assert len(attempts) == 2


def test_robust_reader_bad_trailing_record_ends_epoch_cleanly():
    """A class-based iterator whose LAST record is bad: the skip is
    followed by a genuine StopIteration, which must end the epoch —
    not be misread as generator death."""
    reader = fluid.io.robust(lambda: _FlakyIter(4), max_skips=2)
    assert list(reader()) == [0, 1, 2]  # record 3 skipped, clean end


def test_robust_reader_deterministic_generator_failure_raises_loudly():
    """A generator record that fails EVERY replay can't be skipped
    (fast-forward re-executes it); the restart budget must end in the
    original error, never a silent epoch truncation."""

    def gen_reader():
        for i in range(6):
            if i == 3:  # deterministic: fails on every replay
                raise IOError("permanently bad record")
            yield i

    reader = fluid.io.robust(gen_reader, max_skips=100, max_restarts=3)
    it = reader()
    assert [next(it) for _ in range(3)] == [0, 1, 2]
    with pytest.raises(IOError, match="permanently bad"):
        list(it)


def test_robust_reader_bounded_failures_reraise():
    def all_bad():
        def it():
            raise IOError("dead source")
            yield  # pragma: no cover

        return it()

    reader = fluid.io.robust(all_bad, max_skips=3, max_restarts=100)
    with pytest.raises(IOError):
        list(reader())


# ---------------------------------------------------------------------------
# crash-consistent checkpoints
# ---------------------------------------------------------------------------


def _ckpt_model():
    main, startup = Program(), Program()
    with program_guard(main, startup):
        x = fluid.data("x", shape=[-1, 4])
        pred = fluid.layers.fc(x, size=3, num_flatten_dims=1)
    return main, startup, pred


def _saved_checkpoints(tmp_path, steps=3):
    main, startup, _ = _ckpt_model()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        ck = AutoCheckpoint(exe, main, str(tmp_path), save_interval_steps=1,
                            max_to_keep=10)
        snaps = {}
        for step in range(steps):
            # mutate a param so each checkpoint is distinguishable
            name = ck._persistable_names()[0]
            arr = np.asarray(scope.find_var(name)).copy()
            arr += 1.0
            scope.set(name, arr)
            snaps[step] = arr.copy()
            ck.save(step, blocking=True)
        ck.close()
    return main, snaps


def test_checkpoint_manifest_written_and_verifies(tmp_path):
    _saved_checkpoints(tmp_path, steps=2)
    d = str(tmp_path / "ckpt_1")
    assert os.path.exists(os.path.join(d, "manifest.json"))
    step, arrays = verify_checkpoint(d)
    assert step == 1 and arrays
    with open(os.path.join(d, "manifest.json")) as f:
        man = json.load(f)
    assert set(man["arrays"]) == set(arrays)
    assert man["files"]["state.npz"]["size"] == os.path.getsize(
        os.path.join(d, "state.npz")
    )


def test_corrupted_latest_falls_back_and_quarantines(tmp_path):
    """Satellite: `latest` points at a corrupted checkpoint; resume()
    must quarantine it and restore the previous valid one."""
    main, snaps = _saved_checkpoints(tmp_path, steps=3)
    corrupt_file(str(tmp_path / "ckpt_2" / "state.npz"))
    with pytest.raises(CheckpointCorruptError):
        verify_checkpoint(str(tmp_path / "ckpt_2"))
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        ck = AutoCheckpoint(None, main, str(tmp_path))
        start = ck.resume()
        assert start == 2  # fell back to ckpt_1
        pname = [v.name for v in main.global_block().vars.values()
                 if v.persistable][0]
        np.testing.assert_array_equal(
            np.asarray(scope.find_var(pname)), snaps[1]
        )
    assert os.path.isdir(str(tmp_path / "ckpt_2.corrupt"))
    assert not os.path.exists(str(tmp_path / "ckpt_2"))


def test_truncated_state_detected_as_torn_write(tmp_path):
    _saved_checkpoints(tmp_path, steps=2)
    corrupt_file(str(tmp_path / "ckpt_1" / "state.npz"), mode="truncate")
    with pytest.raises(CheckpointCorruptError, match="torn write"):
        verify_checkpoint(str(tmp_path / "ckpt_1"))
    assert newest_valid_checkpoint(str(tmp_path), quarantine=False) == "ckpt_0"


def test_crash_between_state_write_and_latest_update(tmp_path):
    """Satellite: a crash AFTER the checkpoint dir is complete but BEFORE
    the `latest` pointer swings. The pointer update is the COMMIT point:
    resume() falls back to the previous valid (committed) checkpoint and
    the uncommitted new entry is ignored — never half-trusted."""
    main, snaps = _saved_checkpoints(tmp_path, steps=2)
    faults.configure([
        {"site": "checkpoint.before_latest", "action": "raise",
         "exc": "fault"},
    ])
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        ck = AutoCheckpoint(exe, main, str(tmp_path), save_interval_steps=1)
        start = ck.resume()
        assert start == 2
        with pytest.raises(InjectedFault):
            ck.save(5, blocking=True)  # "crash" at the worst moment
    faults.reset()
    # the pointer still names ckpt_1 (the save never committed); the new
    # dir is complete on disk but resume honors the commit point
    with open(tmp_path / "latest") as f:
        assert f.read().strip() == "ckpt_1"
    assert verify_checkpoint(str(tmp_path / "ckpt_5"))[0] == 5
    scope2 = fluid.Scope()
    with fluid.scope_guard(scope2):
        assert load_checkpoint(str(tmp_path), scope=scope2) == 2
    # but when the POINTER TARGET is lost too (the torn-latest case),
    # the chain walk recovers the newest complete entry instead of
    # starting from scratch
    import shutil

    shutil.rmtree(str(tmp_path / "ckpt_1"))
    scope3 = fluid.Scope()
    with fluid.scope_guard(scope3):
        assert load_checkpoint(str(tmp_path), scope=scope3) == 6


def test_crash_mid_state_write_leaves_only_tmp_debris(tmp_path):
    """A crash DURING the state write leaves a .tmp dir the chain never
    considers; resume() uses the previous checkpoint untouched."""
    main, snaps = _saved_checkpoints(tmp_path, steps=2)
    faults.configure([
        {"site": "checkpoint.before_rename", "action": "raise",
         "exc": "fault"},
    ])
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        ck = AutoCheckpoint(exe, main, str(tmp_path), save_interval_steps=1)
        ck.resume()
        with pytest.raises(InjectedFault):
            ck.save(7, blocking=True)
    faults.reset()
    assert os.path.isdir(str(tmp_path / "ckpt_7.tmp"))
    assert not os.path.isdir(str(tmp_path / "ckpt_7"))
    scope2 = fluid.Scope()
    with fluid.scope_guard(scope2):
        assert load_checkpoint(str(tmp_path), scope=scope2) == 2


def test_autocheckpoint_close_surfaces_async_failure(tmp_path):
    """Satellite: a failed async write must raise at close() — and when
    the snapshot is still in memory, close() first retries it as a
    final blocking save (only raising if that fails too)."""
    main, _ = _saved_checkpoints(tmp_path, steps=1)
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())

    # (a) transient failure: close() recovers via the final blocking save
    faults.configure([{"site": "checkpoint.io", "action": "raise",
                       "times": 1}])
    with fluid.scope_guard(scope):
        ck = AutoCheckpoint(exe, main, str(tmp_path), save_interval_steps=1,
                            retry=RetryPolicy(max_attempts=1))
        ck.resume()
        ck.save(10)  # async write fails once
        ck._join()
        assert ck._last_error is not None
        ck.close()  # retries blocking -> succeeds, no raise
    faults.reset()
    with open(tmp_path / "latest") as f:
        assert f.read().strip() == "ckpt_10"

    # (b) persistent failure: close() must raise, not swallow
    faults.configure([{"site": "checkpoint.io", "action": "raise",
                       "times": -1}])
    with fluid.scope_guard(scope):
        ck2 = AutoCheckpoint(exe, main, str(tmp_path), save_interval_steps=1,
                             retry=RetryPolicy(max_attempts=1))
        ck2.save(11)
        with pytest.raises(RuntimeError, match="checkpoint write failed"):
            ck2.close()
    faults.reset()


def test_checkpoint_io_retries_transient_faults(tmp_path):
    """The default retry policy absorbs a transient IO failure without
    surfacing anything."""
    main, _ = _saved_checkpoints(tmp_path, steps=1)
    faults.configure([{"site": "checkpoint.io", "action": "raise",
                       "times": 1}])
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        ck = AutoCheckpoint(
            exe, main, str(tmp_path), save_interval_steps=1,
            retry=RetryPolicy(max_attempts=3, base_delay_s=0.001),
        )
        ck.resume()
        ck.save(20, blocking=True)  # retried internally, no raise
        ck.close()
    faults.reset()
    assert verify_checkpoint(str(tmp_path / "ckpt_20"))[0] == 20


# ---------------------------------------------------------------------------
# io.py separate-files CRC manifest
# ---------------------------------------------------------------------------


def test_save_load_vars_crc_detects_corruption(tmp_path):
    main, startup, pred = _ckpt_model()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    d = str(tmp_path / "vars")
    with fluid.scope_guard(scope):
        exe.run(startup)
        names = fluid.io.save_persistables(exe, d, main_program=main)
        assert names
        with open(os.path.join(d, "__manifest__.json")) as f:
            man = json.load(f)
        assert set(man["crc32"]) == set(names)
        # clean round trip passes verification
        fluid.io.load_persistables(exe, d, main_program=main)
        # flip payload bytes in one .npy: load must fail naming the var
        victim = names[0]
        corrupt_file(
            os.path.join(d, victim.replace("/", "_") + ".npy"),
            offset=200,  # past the .npy header, inside the payload
        )
        with pytest.raises(fluid.EnforceError, match=victim):
            fluid.io.load_persistables(exe, d, main_program=main)


# ---------------------------------------------------------------------------
# fail-fast gang launcher
# ---------------------------------------------------------------------------


def _write_script(tmp_path, name, body):
    p = str(tmp_path / name)
    with open(p, "w") as f:
        f.write(textwrap.dedent(body))
    return p


def test_launch_procs_fail_fast_terminates_survivors(tmp_path):
    """Satellite: rank 1 crashes immediately; the old sequential wait
    would block 60s on rank 0 — fail-fast must terminate it at once."""
    from paddle_tpu.distributed.launch import launch_procs

    script = _write_script(tmp_path, "gang.py", """
        import os, sys, time
        rank = int(os.environ["PADDLE_TRAINER_ID"])
        if rank == 1:
            sys.exit(3)
        time.sleep(60)
    """)
    t0 = time.monotonic()
    codes = launch_procs([script], nproc=2)
    wall = time.monotonic() - t0
    assert wall < 30, f"fail-fast took {wall:.1f}s"
    assert codes[1] == 3
    assert codes[0] != 0  # terminated, not completed


def test_launch_procs_clean_gang_unchanged(tmp_path):
    from paddle_tpu.distributed.launch import launch_procs

    script = _write_script(tmp_path, "ok.py", """
        import sys
        sys.exit(0)
    """)
    assert launch_procs([script], nproc=2) == [0, 0]


# ---------------------------------------------------------------------------
# gang supervisor
# ---------------------------------------------------------------------------


def test_supervisor_restarts_crashed_gang(tmp_path):
    marker = str(tmp_path / "crashed_once")
    script = _write_script(tmp_path, "worker.py", """
        import os, sys
        marker = sys.argv[1]
        if not os.path.exists(marker):
            open(marker, "w").close()
            sys.exit(7)
        sys.exit(0)
    """)
    sup = GangSupervisor([script, marker], nproc=1, max_restarts=2,
                         restart_backoff_s=0.05)
    codes = sup.run()
    assert codes == [0]
    assert sup.restarts == 1
    kinds = [e["kind"] for e in sup.events]
    assert kinds == ["gang_start", "rank_exit", "restart", "gang_start",
                     "gang_ok"]
    exit_ev = next(e for e in sup.events if e["kind"] == "rank_exit")
    assert exit_ev["rank"] == 0 and exit_ev["code"] == 7


def test_supervisor_restart_budget_exhausted(tmp_path):
    script = _write_script(tmp_path, "always_dies.py", """
        import sys
        sys.exit(5)
    """)
    sup = GangSupervisor([script], nproc=1, max_restarts=1,
                         restart_backoff_s=0.05)
    with pytest.raises(GangFailedError) as ei:
        sup.run()
    assert ei.value.codes == [5]
    kinds = [e["kind"] for e in ei.value.events]
    assert kinds.count("rank_exit") == 2  # initial + 1 restart
    assert kinds[-1] == "gang_failed"


def test_supervisor_detects_hang_via_heartbeat(tmp_path):
    """First incarnation ticks once then wedges; the supervisor declares
    the hang after hang_timeout_s and restarts; the second incarnation
    exits cleanly."""
    marker = str(tmp_path / "hung_once")
    script = _write_script(tmp_path, "hang.py", """
        import os, sys, time
        marker = sys.argv[1]
        hb = os.environ["PADDLE_RESILIENCE_HEARTBEAT_DIR"]
        rank = os.environ["PADDLE_TRAINER_ID"]
        with open(os.path.join(hb, "hb_" + rank), "w") as f:
            f.write("tick")
        if not os.path.exists(marker):
            open(marker, "w").close()
            time.sleep(60)  # wedge: no further ticks
        sys.exit(0)
    """)
    sup = GangSupervisor([script, marker], nproc=1, max_restarts=1,
                         restart_backoff_s=0.05, hang_timeout_s=1.0,
                         heartbeat_dir=str(tmp_path / "hb"))
    t0 = time.monotonic()
    codes = sup.run()
    assert codes == [0]
    assert time.monotonic() - t0 < 30
    hang_ev = next(e for e in sup.events if e["kind"] == "hang")
    assert hang_ev["rank"] == 0 and hang_ev["age_s"] >= 1.0


def test_heartbeat_tick_helper(tmp_path, monkeypatch):
    from paddle_tpu.resilience.supervisor import (
        HEARTBEAT_DIR_ENV,
        heartbeat_tick,
    )

    monkeypatch.delenv(HEARTBEAT_DIR_ENV, raising=False)
    assert heartbeat_tick() is False  # no supervisor: inert
    monkeypatch.setenv(HEARTBEAT_DIR_ENV, str(tmp_path))
    monkeypatch.setenv("PADDLE_TRAINER_ID", "3")
    assert heartbeat_tick() is True
    assert os.path.exists(str(tmp_path / "hb_3"))


# ---------------------------------------------------------------------------
# lookup-path retry
# ---------------------------------------------------------------------------


def test_lookup_pull_push_retry_transient_faults():
    from paddle_tpu.distributed.lookup import RemoteLookupContext

    class FakeClient:
        def __init__(self):
            self.pulls = 0
            self.pushes = 0

        def pull_sparse(self, table_id, ids, dim):
            self.pulls += 1
            if self.pulls < 3:
                raise ConnectionError("blip")
            return np.arange(len(ids) * dim, dtype=np.float32).reshape(
                len(ids), dim
            )

        def push_sparse(self, table_id, ids, grads, lr):
            self.pushes += 1
            if self.pushes < 2:
                raise ConnectionError("blip")

    client = FakeClient()
    ctx = RemoteLookupContext(client)
    ctx.register("emb", table_id=0, dim=4)
    rows = ctx.pull("emb", np.array([5, 9], dtype=np.int64))
    assert rows.shape == (2, 4)
    assert client.pulls == 3  # two transient failures retried
    ctx.push("emb", np.array([5], dtype=np.int64),
             np.ones((1, 4), dtype=np.float32))
    assert client.pushes == 2
    assert ctx.stats["pushes"] == 1
    ctx.close()


# ---------------------------------------------------------------------------
# serving replica circuit breaker
# ---------------------------------------------------------------------------


def _breaker_engine(tmp_path, rng, threshold=2, cooldown_s=0.4):
    from paddle_tpu import inference
    from paddle_tpu.serving import BucketLattice, ServingEngine

    main, startup = Program(), Program()
    with program_guard(main, startup):
        x = fluid.data("x", [-1, 4])
        pred = fluid.layers.fc(x, 3)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        model_dir = os.path.join(str(tmp_path), "model")
        fluid.io.save_inference_model(model_dir, ["x"], [pred], exe,
                                      main_program=main)
    config = inference.Config(model_dir)
    config.disable_tpu()
    lattice = BucketLattice([1, 2])
    config.set_serving_buckets(lattice.batch_sizes, lattice.seq_lens)
    return ServingEngine(
        config, lattice=lattice, num_replicas=1, max_wait_ms=1.0,
        breaker_threshold=threshold, breaker_cooldown_s=cooldown_s,
    )


def test_serving_breaker_quarantines_and_readmits(tmp_path, rng):
    """Acceptance: force the predictor to fail K times -> the breaker
    opens (quarantine); after the cooldown the next batch is a probe
    that re-admits the replica; every lifecycle counter matches
    exactly and flows through stats() (the C ABI JSON surface)."""
    from paddle_tpu.serving import RequestError

    K = 2
    engine = _breaker_engine(tmp_path, rng, threshold=K, cooldown_s=0.4)
    engine.start()
    try:
        rep = engine._replicas[0]
        healthy_run = rep.run_batch

        def broken(feeds):
            raise RuntimeError("forced replica failure")

        x = rng.randn(1, 4).astype("float32")

        # phase A: K consecutive batch failures open the breaker
        rep.run_batch = broken
        for _ in range(K):
            with pytest.raises(RequestError):
                engine.submit({"x": x}).result(timeout=30)
        stats = engine.stats()
        assert stats["batch_failures"] == K
        assert stats["breaker_opened"] == 1
        assert stats["breaker_states"] == ["open"]
        assert stats["breaker_open_replicas"] == 1
        assert stats["failed"] == K

        # phase B: heal the replica; a request submitted DURING the
        # cooldown waits, is served by the probe, and closes the breaker
        rep.run_batch = healthy_run
        t0 = time.perf_counter()
        resp = engine.submit({"x": x})
        out = resp.result(timeout=30)
        waited = time.perf_counter() - t0
        assert waited >= 0.2  # sat out (most of) the cooldown
        np.testing.assert_array_equal(
            out[engine.predictor.get_output_names()[0]],
            engine.predictor.run([x])[0],
        )
        stats = engine.stats()
        assert stats["breaker_probes"] == 1
        assert stats["breaker_closed"] == 1
        assert stats["breaker_states"] == ["closed"]
        assert stats["breaker_open_replicas"] == 0
        assert stats["completed"] == 1

        # phase C: relapse -> reopen via a FAILED probe
        rep.run_batch = broken
        for _ in range(K):
            with pytest.raises(RequestError):
                engine.submit({"x": x}).result(timeout=30)
        assert engine.stats()["breaker_opened"] == 2
        with pytest.raises(RequestError):
            engine.submit({"x": x}).result(timeout=30)  # failing probe
        stats = engine.stats()
        assert stats["breaker_probes"] == 2
        assert stats["breaker_reopened"] == 1
        assert stats["breaker_states"] == ["open"]

        # phase D: heal again; cooldown probe re-admits
        rep.run_batch = healthy_run
        engine.submit({"x": x}).result(timeout=30)
        stats = engine.stats()
        assert stats["breaker_probes"] == 3
        assert stats["breaker_closed"] == 2
        assert stats["breaker_states"] == ["closed"]
    finally:
        engine.shutdown()


def test_serving_breaker_counters_in_capi_stats_json(tmp_path, rng):
    """The C ABI surface (serving_stats_json) carries the breaker
    counters — C/Go front-ends see quarantine state without new ABI."""
    engine = _breaker_engine(tmp_path, rng)
    engine.start()
    try:
        from paddle_tpu.inference import capi_bridge as bridge

        handle = bridge._ServingHandle(engine)
        stats = json.loads(bridge.serving_stats_json(handle))
        for key in ("batch_failures", "breaker_opened", "breaker_probes",
                    "breaker_closed", "breaker_reopened",
                    "breaker_open_replicas", "breaker_states"):
            assert key in stats, key
    finally:
        engine.shutdown()


def test_serving_faults_site_forces_batch_failure(tmp_path, rng):
    """The chaos harness can break serving without monkeypatching: the
    serving.run_batch fault site fails the batch AND the isolation
    re-run, so the request fails and the breaker counts one batch
    failure."""
    from paddle_tpu.serving import RequestError

    engine = _breaker_engine(tmp_path, rng, threshold=5)
    engine.start()
    try:
        faults.configure([
            {"site": "serving.run_batch", "action": "raise", "times": 2},
        ])
        x = rng.randn(1, 4).astype("float32")
        with pytest.raises(RequestError):
            engine.submit({"x": x}).result(timeout=30)
        assert engine.stats()["batch_failures"] == 1
        faults.reset()
        out = engine.submit({"x": x}).result(timeout=30)
        assert out is not None
    finally:
        faults.reset()
        engine.shutdown()


# ---------------------------------------------------------------------------
# chaos CI hook
# ---------------------------------------------------------------------------


def test_chaos_train_smoke_cli():
    """tools/chaos_train.py --smoke: injected worker kill + corrupted
    newest checkpoint -> supervised auto-restart, quarantine, resume,
    and bit-identical final parameters vs the uninterrupted reference."""
    env = dict(os.environ)
    env["PADDLE_TPU_FORCE_CPU"] = "1"
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PADDLE_TPU_FAULTS", None)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "chaos_train.py"),
         "--smoke"],
        capture_output=True, text=True, timeout=560, env=env,
    )
    assert proc.returncode == 0, proc.stdout[-3000:] + proc.stderr[-3000:]
    assert "CHAOS_OK" in proc.stdout
    report = json.loads(
        [l for l in proc.stdout.splitlines() if l.startswith("{")][0]
    )
    extra = report["extra"]
    assert extra["injected_kills"] == 1
    assert extra["restarts"] >= 1
    assert extra["quarantined"]
    assert extra["bit_identical_to_reference"] is True


# ---------------------------------------------------------------------------
# multi-host format-2 manifest merge (PR 8 satellite): rank 0 folds every
# host's shard index into the manifest; a missing host fails LOUDLY at
# save (index never published) or at verify (file listed but absent)
# ---------------------------------------------------------------------------


def _two_host_shard_snaps(dim=4):
    """One [4, dim] array split rows 0-1 (host 0) / 2-3 (host 1)."""
    from paddle_tpu.incubate.checkpoint import _ShardSnap

    full = np.arange(4 * dim, dtype=np.float32).reshape(4, dim)
    host0 = _ShardSnap((4, dim), "float32", "ep(2)",
                       [((0, 0), (2, dim), full[:2])])
    host1 = _ShardSnap((4, dim), "float32", "ep(2)",
                       [((2, 0), (4, dim), full[2:])])
    return full, host0, host1


def _multihost_save(tmp_path, monkeypatch, write_host1_index=True,
                    timeout="1"):
    """Simulate a 2-host save: pre-place host 1's shard file + index in
    the tmp dir (hosts share the checkpoint FS), then run the rank-0
    save which must merge host 1's index into the manifest."""
    from paddle_tpu.incubate import checkpoint as ckpt_mod
    from paddle_tpu.incubate.checkpoint import _write_shard_file

    main, startup = Program(), Program()
    with program_guard(main, startup):
        fluid.data("x", shape=[-1, 2])
    full, host0_snap, host1_snap = _two_host_shard_snaps()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        monkeypatch.setattr(ckpt_mod, "_process_count", lambda: 2)
        monkeypatch.setenv("PADDLE_TPU_CKPT_MERGE_TIMEOUT", timeout)
        tmp = str(tmp_path / "ckpt_0.tmp")
        os.makedirs(tmp, exist_ok=True)
        if write_host1_index:
            _write_shard_file(tmp, {"big": host1_snap}, 1,
                              write_index=True)
        ck = AutoCheckpoint(exe, main, str(tmp_path),
                            save_interval_steps=1, scope=scope)
        # rank 0 contributes its own shard of the same array
        snap = {"w0": np.ones(2, "f"), "big": host0_snap}
        ck._write(0, snap)
    return full


def test_multihost_manifest_merge_roundtrip(tmp_path, monkeypatch):
    full = _multihost_save(tmp_path, monkeypatch)
    d = str(tmp_path / "ckpt_0")
    with open(os.path.join(d, "manifest.json")) as f:
        man = json.load(f)
    assert man["format"] == 2
    # both hosts' files are manifest-listed with CRCs; the array's
    # shard list carries blocks from BOTH hosts
    assert {"shards_p0.npz", "shards_p1.npz"} <= set(man["files"])
    files = {s["file"] for s in man["sharded"]["big"]["shards"]}
    assert files == {"shards_p0.npz", "shards_p1.npz"}
    # the merged index sidecar is not part of the committed checkpoint
    assert not any(n.endswith(".index.json") for n in os.listdir(d))
    step, arrays = verify_checkpoint(d)
    assert step == 0
    np.testing.assert_array_equal(arrays["big"], full)


def test_multihost_missing_host_fails_save_loudly(tmp_path, monkeypatch):
    with pytest.raises(CheckpointCorruptError, match="host 1/2"):
        _multihost_save(tmp_path, monkeypatch, write_host1_index=False)
    # nothing committed: no ckpt_0, no latest pointer
    assert not os.path.exists(tmp_path / "ckpt_0")
    assert not os.path.exists(tmp_path / "latest")


def test_multihost_lost_shard_file_fails_verification(tmp_path,
                                                      monkeypatch):
    """The merged manifest lists host 1's file — losing it after commit
    is DETECTED, never silently-thinned coverage."""
    _multihost_save(tmp_path, monkeypatch)
    d = str(tmp_path / "ckpt_0")
    os.remove(os.path.join(d, "shards_p1.npz"))
    with pytest.raises(CheckpointCorruptError, match="shards_p1.npz"):
        verify_checkpoint(d)


def test_nonchief_host_writes_shards_and_index_only(tmp_path,
                                                    monkeypatch):
    from paddle_tpu.incubate import checkpoint as ckpt_mod

    main, startup = Program(), Program()
    with program_guard(main, startup):
        fluid.data("x", shape=[-1, 2])
    _full, _h0, host1_snap = _two_host_shard_snaps()
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        monkeypatch.setattr(ckpt_mod, "_process_index", lambda: 1)
        monkeypatch.setattr(ckpt_mod, "_process_count", lambda: 2)
        ck = AutoCheckpoint(exe, main, str(tmp_path),
                            save_interval_steps=1, scope=scope)
        ck._write(3, {"w0": np.ones(2, "f"), "big": host1_snap})
    tmp = tmp_path / "ckpt_3.tmp"
    assert sorted(os.listdir(tmp)) == ["shards_p1.index.json",
                                       "shards_p1.npz"]
    # no manifest, no meta, no rename, no latest — the chief owns those
    assert not os.path.exists(tmp_path / "ckpt_3")
    assert not os.path.exists(tmp_path / "latest")
