"""IR-path pipeline/hybrid parallelism tests (pipeline_stack op +
PipelinedStack builder + gpt_ir model).

reference: python/paddle/fluid/optimizer.py:3414 PipelineOptimizer /
section_worker.cc:142 — here the GPipe schedule lives inside the compiled
step (ops/pipeline.py) and runs over the virtual 8-device mesh.
"""

import numpy as np
import pytest

pytestmark = pytest.mark.slow

import jax

import paddle_tpu as fluid
from paddle_tpu.core.ir import Program, program_guard
from paddle_tpu.parallel.env import make_mesh


def _build_stack_model(num_layers=4, num_microbatches=2):
    B, S, H = 8, 4, 16
    main, startup = Program(), Program()
    with program_guard(main, startup):
        x = fluid.data("x", shape=[B, S, H])
        y = fluid.data("y", shape=[B, S, H])
        stack = fluid.layers.PipelinedStack(
            num_layers=num_layers, num_microbatches=num_microbatches
        )
        with stack.layer():
            h = stack.input(x)
            w = stack.layer_param([H, H])
            b = stack.layer_param([H], is_bias=True)
            hp = fluid.layers.relu(
                fluid.layers.elementwise_add(fluid.layers.matmul(h, w), b)
            )
            stack.output(hp)
        out = stack()
        loss = fluid.layers.mean(
            fluid.layers.square(fluid.layers.elementwise_sub(out, y))
        )
        fluid.optimizer.SGD(learning_rate=0.05).minimize(loss)
    return main, startup, loss, stack


def _snapshot_params(exe, main, startup):
    s = fluid.Scope()
    with fluid.scope_guard(s):
        exe.run(startup)
        return {
            p.name: np.asarray(s.find_var(p.name))
            for p in main.all_parameters()
        }


def _run_arm(exe, main, startup, loss, prog, feed, pvals, steps=4):
    sc = fluid.Scope()
    with fluid.scope_guard(sc):
        exe.run(startup)
        # map snapshot values by CREATION ORDER: arms built separately get
        # different unique_name suffixes for structurally-identical params
        own = [p.name for p in main.all_parameters()]
        for n, v in zip(own, pvals.values()):
            assert np.asarray(sc.find_var(n)).shape == v.shape, (n, v.shape)
            sc.set(n, v)
        return [
            float(np.asarray(exe.run(prog, feed=feed, fetch_list=[loss])[0])[0])
            for _ in range(steps)
        ]


def test_pipeline_stack_mesh_parity(rng):
    """dp=2 x stage=4 pipelined run == single-device run, same init."""
    feed = {
        "x": rng.randn(8, 4, 16).astype("float32"),
        "y": rng.randn(8, 4, 16).astype("float32"),
    }
    main, startup, loss, stack = _build_stack_model()
    exe = fluid.Executor(fluid.CPUPlace())
    pvals = _snapshot_params(exe, main, startup)
    ref = _run_arm(exe, main, startup, loss, main, feed, pvals)
    mesh = make_mesh((2, 4), ("data", "stage"))
    prog = fluid.CompiledProgram(main).with_parallel(
        mesh=mesh, loss_name=loss.name,
        param_specs=stack.param_spec_overrides(),
    )
    got = _run_arm(exe, main, startup, loss, prog, feed, pvals)
    np.testing.assert_allclose(ref, got, rtol=2e-4, atol=1e-7)


def test_pipeline_stack_microbatch_counts(rng):
    """num_microbatches changes the schedule, not the math (grads are exact
    in GPipe — microbatches are just batch splits of a mean loss)."""
    feed = {
        "x": rng.randn(8, 4, 16).astype("float32"),
        "y": rng.randn(8, 4, 16).astype("float32"),
    }
    curves = []
    exe = fluid.Executor(fluid.CPUPlace())
    pvals = None
    for mb in (2, 4):
        main, startup, loss, stack = _build_stack_model(num_microbatches=mb)
        if pvals is None:
            pvals = _snapshot_params(exe, main, startup)
        mesh = make_mesh((2, 4), ("data", "stage"))
        prog = fluid.CompiledProgram(main).with_parallel(
            mesh=mesh, loss_name=loss.name,
            param_specs=stack.param_spec_overrides(),
        )
        curves.append(_run_arm(exe, main, startup, loss, prog, feed, pvals))
    np.testing.assert_allclose(curves[0], curves[1], rtol=2e-4, atol=1e-7)


def test_gpt_ir_hybrid_trains(rng):
    """dp2 x pp2 x tp2 GPT on the Program/Executor path converges."""
    from paddle_tpu.models import gpt_ir

    cfg = gpt_ir.GPTIRConfig(
        vocab_size=64, hidden_size=32, num_layers=4, num_heads=4, tp=2
    )
    main, startup, feeds, loss, stack = gpt_ir.build_gpt_ir(
        cfg, seq_len=16, num_microbatches=2
    )
    mesh = make_mesh((2, 2, 2), ("data", "stage", "model"))
    prog = fluid.CompiledProgram(main).with_parallel(
        mesh=mesh, loss_name=loss.name,
        param_specs=stack.param_spec_overrides(),
    )
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    toks, labs = gpt_ir.synthetic_batch(rng, 8, 16, cfg)
    feed = {"tokens": toks, "labels": labs}
    curve = [
        float(np.asarray(exe.run(prog, feed=feed, fetch_list=[loss])[0])[0])
        for _ in range(6)
    ]
    assert np.isfinite(curve).all()
    assert curve[-1] < curve[0] - 0.2, curve


def test_gpt_ir_tp_parity(rng):
    """tp=2 sharded attention/mlp == tp=1 full math (same global weights)."""
    from paddle_tpu.models import gpt_ir

    feed = None
    curves = []
    exe = fluid.Executor(fluid.CPUPlace())
    pvals = None
    for tp, mesh_shape in ((1, (2, 2, 1)), (2, (2, 2, 2))):
        cfg = gpt_ir.GPTIRConfig(
            vocab_size=64, hidden_size=32, num_layers=4, num_heads=4, tp=tp
        )
        main, startup, feeds, loss, stack = gpt_ir.build_gpt_ir(
            cfg, seq_len=16, num_microbatches=2
        )
        if pvals is None:
            pvals = _snapshot_params(exe, main, startup)
            toks, labs = gpt_ir.synthetic_batch(rng, 8, 16, cfg)
            feed = {"tokens": toks, "labels": labs}
        mesh = make_mesh(mesh_shape, ("data", "stage", "model"))
        prog = fluid.CompiledProgram(main).with_parallel(
            mesh=mesh, loss_name=loss.name,
            param_specs=stack.param_spec_overrides(),
        )
        curves.append(
            _run_arm(exe, main, startup, loss, prog, feed, pvals, steps=3)
        )
    np.testing.assert_allclose(curves[0], curves[1], rtol=5e-4, atol=1e-6)


def test_gpt_ir_flash_parity(rng):
    """VERDICT r3 item 4: the fused sdpa (flash) attention path matches the
    unfused matmul/softmax path on the SAME weights, step for step."""
    from paddle_tpu.models import gpt_ir

    feed, pvals, curves = None, None, []
    exe = fluid.Executor(fluid.CPUPlace())
    for flash in (False, True):
        cfg = gpt_ir.GPTIRConfig(
            vocab_size=64, hidden_size=32, num_layers=2, num_heads=4,
            use_flash_attention=flash,
        )
        main, startup, feeds, loss, stack = gpt_ir.build_gpt_ir(
            cfg, seq_len=16, num_microbatches=2
        )
        if pvals is None:
            pvals = _snapshot_params(exe, main, startup)
            toks, labs = gpt_ir.synthetic_batch(rng, 4, 16, cfg)
            feed = {"tokens": toks, "labels": labs}
        mesh = make_mesh((2, 2, 1), ("data", "stage", "model"))
        prog = fluid.CompiledProgram(main).with_parallel(
            mesh=mesh, loss_name=loss.name,
            param_specs=stack.param_spec_overrides(),
        )
        curves.append(
            _run_arm(exe, main, startup, loss, prog, feed, pvals, steps=4)
        )
    np.testing.assert_allclose(curves[0], curves[1], rtol=2e-4, atol=1e-6)


def test_gpt_ir_flash_no_s2_buffer(rng):
    """With flash on (default), no [1,1,S,S] causal-bias materialization
    exists in the program — S>=512 builds a program whose largest static
    var is O(S), not O(S^2)."""
    from paddle_tpu.models import gpt_ir

    cfg = gpt_ir.GPTIRConfig(
        vocab_size=64, hidden_size=32, num_layers=1, num_heads=2,
        max_seq_len=512,
    )
    main, _, _, loss, _ = gpt_ir.build_gpt_ir(cfg, seq_len=512)
    types = {op.type for b in main.blocks for op in b.ops}
    assert "scaled_dot_product_attention" in types
    for b in main.blocks:
        for v in b.vars.values():
            if v.shape:
                static = [d for d in v.shape if d and d > 0]
                assert int(np.prod(static)) < 512 * 512, (v.name, v.shape)


def test_gpt_ir_hybrid_medium_shape(rng):
    """VERDICT r3 weak item 9: a MEDIUM shape (seq 128, hidden 256) through
    dp2 x pp2 x tp2 on the virtual 8-device mesh — proves the product
    composition survives realistic dims/compile, not just tiny wiring."""
    from paddle_tpu.models import gpt_ir

    cfg = gpt_ir.GPTIRConfig(
        vocab_size=512, hidden_size=256, num_layers=4, num_heads=8, tp=2,
        max_seq_len=128,
    )
    main, startup, feeds, loss, stack = gpt_ir.build_gpt_ir(
        cfg, seq_len=128, num_microbatches=2
    )
    mesh = make_mesh((2, 2, 2), ("data", "stage", "model"))
    prog = fluid.CompiledProgram(main).with_parallel(
        mesh=mesh, loss_name=loss.name,
        param_specs=stack.param_spec_overrides(),
    )
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    toks, labs = gpt_ir.synthetic_batch(rng, 4, 128, cfg)
    curve = [
        float(np.asarray(exe.run(
            prog, feed={"tokens": toks, "labels": labs}, fetch_list=[loss]
        )[0])[0])
        for _ in range(3)
    ]
    assert np.isfinite(curve).all()
    assert curve[-1] < curve[0], curve
