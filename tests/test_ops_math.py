"""Forward + gradient checks for math ops via the OpTest harness."""

import numpy as np
import pytest

from op_test import OpTest


class TestElementwiseAdd(OpTest):
    op_type = "elementwise_add"

    def setup(self, rng):
        x = rng.rand(3, 4).astype("float32")
        y = rng.rand(3, 4).astype("float32")
        self.inputs = {"X": x, "Y": y}
        self.outputs = {"Out": x + y}

    def test_output(self, rng):
        self.setup(rng)
        self.check_output()

    def test_grad(self, rng):
        self.setup(rng)
        self.check_grad(["X", "Y"], "Out")


class TestElementwiseAddBroadcast(OpTest):
    op_type = "elementwise_add"

    def test_axis_broadcast(self, rng):
        x = rng.rand(2, 3, 4).astype("float32")
        y = rng.rand(3).astype("float32")
        self.inputs = {"X": x, "Y": y}
        self.attrs = {"axis": 1}
        self.outputs = {"Out": x + y.reshape(1, 3, 1)}
        self.check_output()


class TestElementwiseMul(OpTest):
    op_type = "elementwise_mul"

    def setup(self, rng):
        x = rng.rand(3, 4).astype("float32") + 0.5
        y = rng.rand(3, 4).astype("float32") + 0.5
        self.inputs = {"X": x, "Y": y}
        self.outputs = {"Out": x * y}

    def test_output(self, rng):
        self.setup(rng)
        self.check_output()

    def test_grad(self, rng):
        self.setup(rng)
        self.check_grad(["X", "Y"], "Out")


class TestMatmul(OpTest):
    op_type = "matmul"

    def setup(self, rng):
        x = rng.rand(4, 5).astype("float32")
        y = rng.rand(5, 3).astype("float32")
        self.inputs = {"X": x, "Y": y}
        self.outputs = {"Out": x @ y}

    def test_output(self, rng):
        self.setup(rng)
        self.check_output()

    def test_grad(self, rng):
        self.setup(rng)
        self.check_grad(["X", "Y"], "Out", max_relative_error=0.01)


class TestMatmulTranspose(OpTest):
    op_type = "matmul"

    def test_output(self, rng):
        x = rng.rand(5, 4).astype("float32")
        y = rng.rand(3, 5).astype("float32")
        self.inputs = {"X": x, "Y": y}
        self.attrs = {"transpose_X": True, "transpose_Y": True}
        self.outputs = {"Out": x.T @ y.T}
        self.check_output()


class TestMul(OpTest):
    op_type = "mul"

    def test_output_and_grad(self, rng):
        x = rng.rand(2, 3, 4).astype("float32")
        y = rng.rand(12, 5).astype("float32")
        self.inputs = {"X": x, "Y": y}
        self.attrs = {"x_num_col_dims": 1}
        self.outputs = {"Out": (x.reshape(2, 12) @ y).reshape(2, 5)}
        self.check_output()
        self.check_grad(["X", "Y"], "Out", max_relative_error=0.01)


class TestReduceSum(OpTest):
    op_type = "reduce_sum"

    def test_output_and_grad(self, rng):
        x = rng.rand(3, 4, 5).astype("float32")
        self.inputs = {"X": x}
        self.attrs = {"dim": [1]}
        self.outputs = {"Out": x.sum(axis=1)}
        self.check_output()
        self.check_grad(["X"], "Out")


class TestReduceMeanAll(OpTest):
    op_type = "reduce_mean"

    def test_output(self, rng):
        x = rng.rand(3, 4).astype("float32")
        self.inputs = {"X": x}
        self.attrs = {"reduce_all": True}
        self.outputs = {"Out": np.array([x.mean()], dtype="float32")}
        self.check_output()


class TestSqrtGrad(OpTest):
    op_type = "sqrt"

    def test_grad(self, rng):
        x = (rng.rand(3, 4) + 0.5).astype("float32")
        self.inputs = {"X": x}
        self.outputs = {"Out": np.sqrt(x)}
        self.check_output()
        self.check_grad(["X"], "Out", max_relative_error=0.01)


class TestScale(OpTest):
    op_type = "scale"

    def test_output(self, rng):
        x = rng.rand(3, 4).astype("float32")
        self.inputs = {"X": x}
        self.attrs = {"scale": 2.5, "bias": 1.0}
        self.outputs = {"Out": x * 2.5 + 1.0}
        self.check_output()


class TestSumOp(OpTest):
    op_type = "sum"

    def test_output(self, rng):
        a = rng.rand(3, 4).astype("float32")
        b = rng.rand(3, 4).astype("float32")
        c = rng.rand(3, 4).astype("float32")
        self.inputs = {"X": [("a", a), ("b", b), ("c", c)]}
        self.outputs = {"Out": a + b + c}
        self.check_output()


class TestTopK(OpTest):
    op_type = "top_k"

    def test_output(self, rng):
        x = rng.rand(4, 10).astype("float32")
        self.inputs = {"X": x}
        self.attrs = {"k": 3}
        vals = np.sort(x, axis=1)[:, ::-1][:, :3]
        self.outputs = {"Out": vals, "Indices": None}
        self.check_output()
