#!/usr/bin/env python
"""Chaos harness for elastic gang training: kill a rank mid-step, shrink
the gang, grow it back — and prove the run is REPLAY-DETERMINISTIC.

The scenario (the acceptance bar for the elasticity subsystem): a
4-rank gang trains a sharded (SpecLayout over local virtual devices)
model over an elastic DataEngine stream with per-step blocking
AutoCheckpoints, under an ElasticGangSupervisor. The fault schedule
(a) hard-kills one rank mid-step (``train.step`` kill — capacity lost,
the supervisor shrinks 4 -> 2) and later (b) preempts a rank of the
shrunk gang (``worker.preempt`` term — the capacity-returns signal, the
supervisor grows 2 -> 4). Each incarnation resumes from the supervisor-
pinned SYNC checkpoint: params + optimizer slots shard-wise via
``resume(shardings=..., step=...)`` (format-2, restored onto a
DIFFERENT local mesh — ranks get 8/world virtual devices), the data
stream via the elastic global-cursor translation (grown ranks pull the
chief's data blob). Every manifest carries the gang generation.

The property gate — replay determinism:

* The elastic run's COMMITTED stream (what each surviving generation
  built on) is reconstructed from per-generation logs, and a fresh
  REFERENCE run is driven phase-by-phase with the SAME (world-size,
  step-range) schedule the elastic run realized — no kills, no
  supervisor. Rank 0's committed loss sequence and every committed
  batch (positions + bytes) must be BIT-IDENTICAL between the two.
* Exactly-once: per epoch, the committed global sample positions tile
  ``[0, consumed)`` with zero gaps and zero duplicates — no sample
  lost or double-consumed across either resize.
* Gang generations are monotone in every rank's checkpoint chain, and
  shard-wise (NamedSharding) restores actually happened at both
  resizes.

``--smoke`` runs the seconds-scale configuration and asserts all of it
— wired into the fast tier (tests/test_elastic.py, which also uses
``--evidence`` output as the ELASTIC_EVIDENCE_r14.json drift gate: one
scenario run serves both, the chaos_serve/chaos_train pattern).

Usage:
  python tools/chaos_elastic.py [--nproc 4] [--min-nproc 2]
      [--steps 16] [--interval 2] [--kill-step 5] [--kill-rank 3]
      [--preempt-step 12] [--smoke] [--json] [--evidence OUT.json]
"""

import argparse
import hashlib
import json
import os
import shutil
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("PADDLE_TPU_FORCE_CPU", "1")
os.environ.setdefault("JAX_PLATFORMS", "cpu")


# ---------------------------------------------------------------------------
# worker: one elastic training rank (also the reference-phase runner)
# ---------------------------------------------------------------------------


def _announce(run_dir, gen, rank, step):
    path = os.path.join(run_dir, f"step_g{gen}_r{rank}")
    with open(path, "w") as f:
        f.write(str(step))


def _barrier(run_dir, gen, rank, world, step, timeout=60.0):
    """Wait until every rank of this generation has announced `step`.
    The data-parallel lockstep collectives would impose: without it,
    free-running ranks drift apart and the realized sync step (the
    newest checkpoint COMMON to all ranks) stops being deterministic.
    A dead rank never advances its counter — survivors block here until
    the supervisor terminates them, which is exactly the wedged-gang
    behavior a dead collective produces."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        ready = True
        for r in range(world):
            if r == rank:
                continue
            try:
                with open(os.path.join(run_dir, f"step_g{gen}_r{r}")) as f:
                    other = int(f.read().strip() or "-1")
            except (OSError, ValueError):
                other = -1
            if other < step:
                ready = False
                break
        if ready:
            return True
        time.sleep(0.005)
    return False


def run_worker(args):
    import numpy as np

    import jax
    from jax.sharding import NamedSharding

    import paddle_tpu as fluid
    from paddle_tpu.dataio import DataEngine, ListSource
    from paddle_tpu.incubate.checkpoint import (
        AutoCheckpoint,
        load_data_state,
    )
    from paddle_tpu.parallel.env import make_mesh
    from paddle_tpu.parallel.spec_layout import SpecLayout
    from paddle_tpu.resilience import faults
    from paddle_tpu.resilience.elastic import (
        elastic_resume_step,
        gang_generation,
    )
    from paddle_tpu.resilience.supervisor import heartbeat_tick

    rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
    world = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
    gen = gang_generation() or 0
    sync = elastic_resume_step()
    ckpt_dir = os.path.join(args.ckpt_base, f"rank{rank}")
    chief_dir = os.path.join(args.ckpt_base, "rank0")
    os.makedirs(args.log_dir, exist_ok=True)

    # -- data: elastic stream over the rank's shard of the global order --
    def transform(i, rng):
        x = (np.full(args.feat, float(i), dtype=np.float32) * 0.01
             + np.float32(rng.random() * 1e-3))
        return (x, np.array([x.sum()], dtype=np.float32))

    source = ListSource(list(range(args.n_samples)), seed=args.seed,
                        rank=rank, world=world)
    engine = DataEngine(source, transform=transform,
                        batch_size=args.batch, drop_last=True,
                        num_workers=args.num_workers, elastic=True)

    # -- model: fc stack sharded over THIS incarnation's local mesh ------
    devices = jax.devices()
    mesh = make_mesh(shape=(1, len(devices)), axis_names=("data", "fsdp"),
                     devices=devices)
    layout = SpecLayout()

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.data("x", shape=[-1, args.feat])
        y = fluid.data("y", shape=[-1, 1])
        h = fluid.layers.fc(x, size=args.hidden, act="relu")
        pred = fluid.layers.fc(h, size=1)
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
        fluid.optimizer.Adam(learning_rate=0.05).minimize(loss)
        feeder = fluid.DataFeeder([x, y])

    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        prog = fluid.CompiledProgram(main).with_parallel(
            mesh=mesh, loss_name=loss.name, spec_layout=layout)
        ck = AutoCheckpoint(exe, main, ckpt_dir,
                            save_interval_steps=args.interval,
                            max_to_keep=32, scope=scope,
                            data_state=engine)
        persistables = [v.name for v in main.global_block().vars.values()
                        if v.persistable]
        target = layout.derive_shardings(
            main, persistables,
            [tuple(np.shape(scope.find_var(n))) for n in persistables],
            mesh)

        data_from_chief = False
        if sync is not None and os.path.isdir(
                os.path.join(ckpt_dir, f"ckpt_{sync}")):
            # surviving rank: params + optimizer slots shard-wise onto
            # THIS mesh (N->M reshape), data position from the same
            # verified manifest (elastic geometry translation inside
            # the engine). A corrupt pinned entry raises: the worker
            # exits nonzero and the supervisor re-validates.
            start = ck.resume(shardings=target, step=sync)
        elif sync is not None:
            # grown rank: no own checkpoint at the sync step — fresh
            # params, data position from the CHIEF's blob translated
            # onto this (world, rank)
            blob = load_data_state(chief_dir, step=sync)
            if blob is not None:
                engine.load_state_dict(blob)
                data_from_chief = True
            start = sync + 1
        else:
            start = ck.resume(shardings=target)

        # format-2 entries come back as mesh-placed jax.Arrays
        # (NamedSharding); plain entries as numpy — counting the former
        # counts exactly the arrays restored shard-wise (r07 pattern)
        sharded_restored = 0
        if start > 0 and not data_from_chief:
            sharded_restored = sum(
                1 for n in persistables
                if isinstance(scope.find_var(n), jax.Array)
                and isinstance(getattr(scope.find_var(n), "sharding",
                                       None), NamedSharding))
        with open(os.path.join(args.log_dir,
                               f"restore_g{gen}_r{rank}.json"), "w") as f:
            json.dump({"start": start, "gen": gen, "rank": rank,
                       "world": world, "ndev": len(devices),
                       "sharded_restored": sharded_restored,
                       "data_from_chief": data_from_chief}, f)
        print(f"ELASTIC_WORKER gen={gen} rank={rank}/{world} "
              f"start={start} ndev={len(devices)} "
              f"sharded_restored={sharded_restored} "
              f"chief_data={data_from_chief}", flush=True)

        log_path = os.path.join(args.log_dir, f"log_g{gen}_r{rank}.jsonl")
        it = iter(engine)
        with open(log_path, "a") as logf:
            for step in range(start, args.steps):
                _announce(args.run_dir, gen, rank, step)
                _barrier(args.run_dir, gen, rank, world, step)
                heartbeat_tick()
                faults.fire("train.step", step=step)
                faults.fire("worker.preempt", step=step)
                try:
                    batch = next(it)
                except StopIteration:
                    it = iter(engine)
                    batch = next(it)
                feed = feeder.feed(batch)
                val = float(np.asarray(
                    exe.run(prog, feed=feed, fetch_list=[loss])[0]
                ).reshape(-1)[0])
                # the batch covers shard positions [cursor-B, cursor) of
                # the suffix cut at `base`: global epoch positions
                # base + j*world + rank
                c0 = engine.cursor - args.batch
                positions = [engine.base + j * world + rank
                             for j in range(c0, engine.cursor)]
                h = hashlib.sha256()
                h.update(np.ascontiguousarray(feed["x"]).tobytes())
                h.update(np.ascontiguousarray(feed["y"]).tobytes())
                logf.write(json.dumps({
                    "gen": gen, "rank": rank, "world": world,
                    "step": step, "epoch": engine.epoch,
                    "positions": positions, "digest": h.hexdigest(),
                    "loss": val.hex(),
                }) + "\n")
                logf.flush()
                ck.maybe_save(step, blocking=True)
        ck.close()
    print(f"ELASTIC_WORKER_DONE gen={gen} rank={rank}", flush=True)
    return 0


# ---------------------------------------------------------------------------
# committed-stream reconstruction
# ---------------------------------------------------------------------------


def _read_logs(log_dir):
    rows = []
    for name in sorted(os.listdir(log_dir)):
        if not (name.startswith("log_g") and name.endswith(".jsonl")):
            continue
        with open(os.path.join(log_dir, name)) as f:
            for line in f:
                rows.append(json.loads(line))
    return rows


def committed_stream(rows):
    """The entries the FINAL run actually built on: generation g's
    entries survive only below the step generation g+1 resumed at (a
    later incarnation re-consumes everything from its sync point, under
    its own geometry)."""
    by_gen = {}
    for r in rows:
        by_gen.setdefault(r["gen"], []).append(r)
    gens = sorted(by_gen)
    starts = {g: min(r["step"] for r in by_gen[g]) for g in gens}
    committed = []
    for i, g in enumerate(gens):
        stop = starts[gens[i + 1]] if i + 1 < len(gens) else None
        for r in by_gen[g]:
            if stop is None or r["step"] < stop:
                committed.append(r)
    return committed


def stream_key(r):
    return (r["step"], r["world"], r["rank"], r["epoch"],
            tuple(r["positions"]), r["digest"], r["loss"])


def stream_digest(committed):
    """sha256 over the committed per-epoch position/sample stream —
    geometry-free, so elastic and reference runs must agree byte for
    byte."""
    entries = sorted(
        (r["epoch"], p, r["digest"])
        for r in committed for p in r["positions"]
    )
    return hashlib.sha256(json.dumps(entries).encode()).hexdigest()


def check_exactly_once(committed):
    """Per epoch, committed positions must tile [0, consumed) exactly:
    zero gaps (lost samples), zero duplicates (double-consumed)."""
    per_epoch = {}
    for r in committed:
        per_epoch.setdefault(r["epoch"], []).extend(r["positions"])
    problems = []
    for ep, poss in sorted(per_epoch.items()):
        s = sorted(poss)
        if len(set(s)) != len(s):
            dupes = sorted({p for p in s if s.count(p) > 1})
            problems.append(f"epoch {ep}: duplicated positions "
                            f"{dupes[:5]}")
        if s != list(range(len(s))):
            missing = sorted(set(range(s[-1] + 1)) - set(s))[:5]
            problems.append(f"epoch {ep}: gaps at positions {missing}")
    return problems, {ep: len(p) for ep, p in sorted(per_epoch.items())}


# ---------------------------------------------------------------------------
# supervisor: the chaos scenario driver
# ---------------------------------------------------------------------------


def worker_args(args, ckpt_base, log_dir, run_dir):
    return [
        os.path.abspath(__file__), "--worker",
        "--steps", str(args.steps), "--interval", str(args.interval),
        "--n-samples", str(args.n_samples), "--batch", str(args.batch),
        "--seed", str(args.seed), "--feat", str(args.feat),
        "--hidden", str(args.hidden),
        "--num-workers", str(args.num_workers),
        "--ckpt-base", ckpt_base, "--log-dir", log_dir,
        "--run-dir", run_dir,
    ]


def run_elastic_leg(args, work):
    """The chaotic leg: ElasticGangSupervisor + fault schedule."""
    from paddle_tpu.resilience.elastic import ElasticGangSupervisor

    ckpt_base = os.path.join(work, "ckpt")
    log_dir = os.path.join(work, "logs")
    run_dir = os.path.join(work, "run")
    for d in (ckpt_base, log_dir, run_dir):
        os.makedirs(d, exist_ok=True)

    schedule = [
        {"site": "train.step", "action": "kill", "at_step": args.kill_step,
         "rank": args.kill_rank, "exit_code": 43, "id": "elastic-kill"},
        {"site": "worker.preempt", "action": "term",
         "at_step": args.preempt_step, "rank": 0, "id": "elastic-preempt"},
    ]

    sup_box = {}

    def capacity():
        """The simulated cluster scheduler: full capacity until the hard
        kill (a host is gone: only min_nproc available), full again once
        the preemption fires (capacity returned)."""
        sup = sup_box["sup"]
        exits = [e for e in sup.events if e["kind"] == "rank_exit"]
        if any(e["code"] not in (0, 43) for e in exits):
            return args.nproc          # preemption seen: capacity back
        if any(e["code"] == 43 for e in exits):
            return args.min_nproc      # host lost
        return args.nproc

    def on_resize(old_world, new_world, sup):
        # surviving hosts pick up the lost ranks' local devices: the
        # per-rank mesh geometry CHANGES across the resize, which is
        # what makes the shard-wise N->M restore a real reshape
        sup.devices_per_proc = max(1, args.devices_total // new_world)

    sup = ElasticGangSupervisor(
        worker_args(args, ckpt_base, log_dir, run_dir),
        nproc=args.nproc, min_nproc=args.min_nproc,
        max_restarts=args.max_restarts, restart_backoff_s=0.2,
        capacity_fn=capacity, capacity_poll_s=0.05,
        on_resize=on_resize,
        devices_per_proc=max(1, args.devices_total // args.nproc),
        checkpoint_dirs=[os.path.join(ckpt_base, f"rank{r}")
                         for r in range(args.nproc)],
        extra_env={
            "PADDLE_TPU_FAULTS": json.dumps(schedule),
            "PADDLE_TPU_FAULT_STATE": os.path.join(work, "fault_state"),
        },
    )
    sup_box["sup"] = sup
    t0 = time.perf_counter()
    codes = sup.run()
    wall = time.perf_counter() - t0
    return {
        "codes": codes, "wall_s": wall, "sup": sup,
        "log_dir": log_dir, "ckpt_base": ckpt_base,
        "events": [{k: v for k, v in e.items() if k != "time"}
                   for e in sup.events],
    }


def realized_schedule(sup, args):
    """[(world, start_step, stop_step, sync)] phases the elastic run
    actually committed — extracted from the supervisor's structured
    events; the reference leg replays exactly this."""
    phases = []
    world = args.nproc
    start = 0
    gen = 0
    for e in sup.events:
        if e["kind"] == "restart":
            sync = e.get("resume_step")
            stop = (sync + 1) if sync is not None else 0
            phases.append({"world": world, "start": start, "stop": stop,
                           "gen": gen, "sync": sync})
            world = e.get("world", world)
            start = stop
            gen = e.get("generation", gen + 1)
    phases.append({"world": world, "start": start, "stop": args.steps,
                   "gen": gen, "sync": phases[-1]["sync"] if phases
                   else None})
    return phases


def run_reference_leg(args, work, phases):
    """The clean leg: replay the realized (world, step-range) schedule
    with NO kills and NO supervisor — fresh dirs, phase by phase, each
    phase resuming from the previous phase's sync checkpoint exactly
    like the elastic incarnations did."""
    from paddle_tpu.distributed.launch import spawn_gang, wait_gang
    from paddle_tpu.resilience.elastic import (
        GANG_GENERATION_ENV,
        RESUME_STEP_ENV,
    )

    ckpt_base = os.path.join(work, "ref_ckpt")
    log_dir = os.path.join(work, "ref_logs")
    run_dir = os.path.join(work, "ref_run")
    for d in (ckpt_base, log_dir, run_dir):
        os.makedirs(d, exist_ok=True)

    base_args = worker_args(args, ckpt_base, log_dir, run_dir)
    for i, ph in enumerate(phases):
        if ph["stop"] <= ph["start"]:
            continue
        extra_env = {
            GANG_GENERATION_ENV: str(ph["gen"]),
            # a phase stops right AFTER its sync step so the next one
            # resumes from the same checkpoint the elastic gang did
            "PADDLE_TPU_FAULTS": "", "PADDLE_TPU_FAULT_STATE": "",
        }
        prev_sync = phases[i - 1]["sync"] if i > 0 else None
        if prev_sync is not None:
            extra_env[RESUME_STEP_ENV] = str(prev_sync)
        phase_args = list(base_args)
        phase_args[phase_args.index("--steps") + 1] = str(ph["stop"])
        procs = spawn_gang(
            phase_args, nproc=ph["world"],
            devices_per_proc=max(1, args.devices_total // ph["world"]),
            extra_env=extra_env)
        codes = wait_gang(procs)
        assert all(c == 0 for c in codes), (
            f"reference phase {i} ({ph}) failed: {codes}")
    return {"log_dir": log_dir, "ckpt_base": ckpt_base}


def run_scenario(args, work):
    from paddle_tpu.incubate.checkpoint import gang_generations

    elastic = run_elastic_leg(args, work)
    sup = elastic["sup"]
    phases = realized_schedule(sup, args)
    ref = run_reference_leg(args, work, phases)

    failures = []

    def check(ok, msg):
        if not ok:
            failures.append(msg)
        return ok

    # -- the run resolved --------------------------------------------------
    check(all(c == 0 for c in elastic["codes"]),
          f"final gang exited nonzero: {elastic['codes']}")
    resize_dirs = [(e["old_world"], e["new_world"], e["direction"])
                   for e in sup.events if e["kind"] == "gang_resize"]
    check((args.nproc, args.min_nproc, "shrink") in resize_dirs,
          f"no shrink {args.nproc}->{args.min_nproc} happened: "
          f"{resize_dirs}")
    check((args.min_nproc, args.nproc, "grow") in resize_dirs,
          f"no grow {args.min_nproc}->{args.nproc} happened: "
          f"{resize_dirs}")
    kill_exits = [e for e in sup.events
                  if e["kind"] == "rank_exit" and e["code"] == 43]
    check(len(kill_exits) == 1,
          f"expected exactly one injected hard kill, saw {kill_exits}")

    # -- replay determinism ------------------------------------------------
    el_rows = _read_logs(elastic["log_dir"])
    el_committed = committed_stream(el_rows)
    ref_rows = _read_logs(ref["log_dir"])
    ref_committed = committed_stream(ref_rows)
    check(len(ref_committed) == len(ref_rows),
          "reference phases overlapped (harness bug)")

    el_keys = sorted(stream_key(r) for r in el_committed)
    ref_keys = sorted(stream_key(r) for r in ref_committed)
    bit_identical = el_keys == ref_keys
    if not bit_identical:
        diff = [(a, b) for a, b in zip(el_keys, ref_keys) if a != b][:3]
        check(False, f"REPLAY DETERMINISM VIOLATED: committed streams "
                     f"differ (sizes {len(el_keys)}/{len(ref_keys)}, "
                     f"first diffs {diff})")

    el_digest = stream_digest(el_committed)
    ref_digest = stream_digest(ref_committed)
    check(el_digest == ref_digest, "stream digests differ")

    # rank-0 committed loss sequence, bit-exact (float hex)
    el_losses = {r["step"]: r["loss"] for r in el_committed
                 if r["rank"] == 0}
    ref_losses = {r["step"]: r["loss"] for r in ref_committed
                  if r["rank"] == 0}
    check(el_losses == ref_losses,
          f"rank-0 loss sequence diverged at steps "
          f"{sorted(s for s in el_losses if el_losses.get(s) != ref_losses.get(s))[:5]}")
    loss_digest = hashlib.sha256(json.dumps(
        sorted(el_losses.items())).encode()).hexdigest()

    # -- exactly-once ------------------------------------------------------
    problems, per_epoch = check_exactly_once(el_committed)
    for p in problems:
        check(False, f"EXACTLY-ONCE VIOLATED: {p}")

    # -- gang generations monotone in every manifest -----------------------
    gens_seen = set()
    for r in range(args.nproc):
        d = os.path.join(elastic["ckpt_base"], f"rank{r}")
        if not os.path.isdir(d):
            continue
        chain = gang_generations(d)
        gens = [g for _, g in chain if g is not None]
        gens_seen.update(gens)
        check(all(g is not None for _, g in chain),
              f"rank{r}: unstamped manifests in an elastic run: {chain}")
        check(gens == sorted(gens),
              f"rank{r}: gang generation not monotone by step: {chain}")
    check(len(gens_seen) >= 3,
          f"expected >= 3 gang generations in the chains, saw "
          f"{sorted(gens_seen)}")

    # -- shard-wise restores actually happened at both resizes -------------
    restores = {}
    for name in os.listdir(elastic["log_dir"]):
        if name.startswith("restore_"):
            with open(os.path.join(elastic["log_dir"], name)) as f:
                r = json.load(f)
            restores[(r["gen"], r["rank"])] = r
    shrink_r0 = restores.get((1, 0), {})
    grow_r0 = restores.get((2, 0), {})
    check(shrink_r0.get("sharded_restored", 0) > 0,
          f"shrink resume was not shard-wise: {shrink_r0}")
    check(grow_r0.get("sharded_restored", 0) > 0,
          f"grow resume was not shard-wise: {grow_r0}")
    check(shrink_r0.get("ndev") != grow_r0.get("ndev"),
          f"mesh geometry never changed across resizes: "
          f"{shrink_r0.get('ndev')} vs {grow_r0.get('ndev')}")
    grown = [r for (g, _), r in restores.items()
             if g == 2 and r.get("data_from_chief")]
    check(len(grown) >= 1,
          "no grown rank translated the chief's data blob")

    report = {
        "scenario": {
            "nproc": args.nproc, "min_nproc": args.min_nproc,
            "steps": args.steps, "interval": args.interval,
            "kill_step": args.kill_step, "kill_rank": args.kill_rank,
            "preempt_step": args.preempt_step,
            "n_samples": args.n_samples, "batch": args.batch,
            "seed": args.seed, "feat": args.feat, "hidden": args.hidden,
            "num_workers": args.num_workers,
            "devices_total": args.devices_total,
        },
        "invariants": {
            "schedule": [{k: ph[k] for k in
                          ("world", "start", "stop", "gen", "sync")}
                         for ph in phases],
            "resizes": resize_dirs,
            "generations": sorted(gens_seen),
            "committed_batches": len(el_committed),
            "samples_per_epoch": per_epoch,
            "lost_or_duplicated": len(problems),
            "bit_identical": bit_identical,
            "stream_digest": el_digest,
            "rank0_loss_digest": loss_digest,
            "shrink_sharded_restored": shrink_r0.get("sharded_restored"),
            "grow_sharded_restored": grow_r0.get("sharded_restored"),
            "grown_ranks_from_chief": len(grown),
        },
        "measured": {
            "wall_s": round(elastic["wall_s"], 1),
            "restarts": sup.restarts,
            "events": [e["kind"] for e in sup.events],
            "ndev_by_gen_rank0": {g: r.get("ndev") for (g, rk), r in
                                  sorted(restores.items()) if rk == 0},
        },
        "failures": failures,
    }
    return report


def _write_evidence(path, report):
    payload = {
        "issue": 14,
        "generated_by": ("python tools/chaos_elastic.py --smoke "
                         "--evidence ELASTIC_EVIDENCE_r14.json"),
        "drift_gates": [
            "tests/test_elastic.py::test_elastic_evidence_r14_committed "
            "(live recompute via --smoke --evidence)",
        ],
        "scenario": report["scenario"],
        "invariants": report["invariants"],
        # informational: timing-dependent, NOT drift-gated
        "measured": report["measured"],
    }
    with open(path, "w") as f:
        json.dump(payload, f, indent=1, sort_keys=True)
        f.write("\n")
    inv = payload["invariants"]
    print(f"wrote {path}: schedule="
          f"{[(p['world'], p['start'], p['stop']) for p in inv['schedule']]} "
          f"bit_identical={inv['bit_identical']} "
          f"lost_or_duplicated={inv['lost_or_duplicated']}")


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--worker", action="store_true",
                    help="internal: run as one elastic training rank")
    ap.add_argument("--nproc", type=int, default=4)
    ap.add_argument("--min-nproc", type=int, default=2)
    ap.add_argument("--steps", type=int, default=16)
    ap.add_argument("--interval", type=int, default=2)
    ap.add_argument("--kill-step", type=int, default=5)
    ap.add_argument("--kill-rank", type=int, default=3)
    ap.add_argument("--preempt-step", type=int, default=12)
    ap.add_argument("--max-restarts", type=int, default=4)
    ap.add_argument("--n-samples", type=int, default=96)
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--seed", type=int, default=11)
    ap.add_argument("--feat", type=int, default=8)
    ap.add_argument("--hidden", type=int, default=16)
    ap.add_argument("--num-workers", type=int, default=2,
                    help="dataio worker threads inside each rank")
    ap.add_argument("--devices-total", type=int, default=8,
                    help="virtual device budget split across ranks")
    ap.add_argument("--ckpt-base", type=str, default=None)
    ap.add_argument("--log-dir", type=str, default=None)
    ap.add_argument("--run-dir", type=str, default=None)
    ap.add_argument("--workdir", type=str, default=None,
                    help="keep artifacts here instead of a tmpdir")
    ap.add_argument("--smoke", action="store_true",
                    help="seconds-scale run + invariant asserts (CI)")
    ap.add_argument("--evidence", metavar="OUT.json",
                    help="write the elastic evidence file")
    ap.add_argument("--json", action="store_true", dest="as_json")
    args = ap.parse_args(argv)

    if args.worker:
        return run_worker(args)
    if args.smoke:
        args.nproc, args.min_nproc = 4, 2
        args.steps, args.interval = 16, 2
        args.kill_step, args.kill_rank, args.preempt_step = 5, 3, 12

    work = args.workdir or tempfile.mkdtemp(prefix="chaos_elastic_")
    t0 = time.perf_counter()
    try:
        report = run_scenario(args, work)
    finally:
        if not args.workdir:
            shutil.rmtree(work, ignore_errors=True)
    wall = time.perf_counter() - t0
    if args.evidence:
        _write_evidence(args.evidence, report)
    if args.as_json:
        print(json.dumps({"pass": not report["failures"], **report,
                          "wall_s": round(wall, 1)}))
    else:
        print(json.dumps(report, indent=1))
    if report["failures"]:
        for f in report["failures"]:
            print(f"CHAOS FAIL: {f}", file=sys.stderr)
        return 1
    inv = report["invariants"]
    print(f"CHAOS_ELASTIC_OK schedule="
          f"{[(p['world'], p['start'], p['stop']) for p in inv['schedule']]} "
          f"committed={inv['committed_batches']} lost=0 dup=0 "
          f"generations={inv['generations']} wall={wall:.1f}s")
    return 0


if __name__ == "__main__":
    sys.exit(main())
