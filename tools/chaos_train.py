#!/usr/bin/env python
"""Chaos harness: a supervised training job run under an injected fault
schedule, asserting it auto-recovers.

The scenario (the acceptance bar for the resilience subsystem): an
nproc-rank gang trains a deterministic model with crash-consistent
AutoCheckpoints while the fault schedule (a) SIGKILL-equivalent kills
one rank at a fixed step and (b) corrupts the survivor's newest
checkpoint before the supervised relaunch. The GangSupervisor must
terminate + relaunch the gang within its restart budget, the relaunched
workers must quarantine the corrupt entry and resume from the newest
VALID checkpoint, and rank 0's final parameters must be BIT-IDENTICAL
to an uninterrupted reference run resumed from that same (post-
corruption) checkpoint state.

`--smoke` runs the seconds-scale configuration and asserts all of it —
wired into the fast test tier by tests/test_resilience.py, the same
pattern as tools/bench_serving.py.

Usage:
  python tools/chaos_train.py [--nproc 2] [--steps 30] [--interval 5]
      [--kill-step 12] [--kill-rank 1] [--max-restarts 2] [--smoke]
"""

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("PADDLE_TPU_FORCE_CPU", "1")
os.environ.setdefault("JAX_PLATFORMS", "cpu")


# ---------------------------------------------------------------------------
# worker: one deterministic training rank (also the reference runner)
# ---------------------------------------------------------------------------


def run_worker(args):
    import numpy as np

    import paddle_tpu as fluid
    from paddle_tpu.core.ir import Program, program_guard
    from paddle_tpu.incubate.checkpoint import AutoCheckpoint
    from paddle_tpu.resilience import faults
    from paddle_tpu.resilience.supervisor import heartbeat_tick

    rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
    ckpt_dir = os.path.join(args.ckpt_base, f"rank{rank}")

    main, startup = Program(), Program()
    with program_guard(main, startup):
        x = fluid.data("x", shape=[-1, args.feat])
        y = fluid.data("y", shape=[-1, 1])
        pred = fluid.layers.fc(x, size=1, num_flatten_dims=1)
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
        fluid.optimizer.Adam(learning_rate=0.05).minimize(loss)

    rng = np.random.RandomState(1234 + rank)
    feed = {
        "x": rng.randn(16, args.feat).astype("float32"),
        "y": rng.randn(16, 1).astype("float32"),
    }
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        ck = AutoCheckpoint(exe, main, ckpt_dir,
                            save_interval_steps=args.interval,
                            max_to_keep=8)
        start = ck.resume()
        print(f"CHAOS_WORKER rank={rank} start_step={start}", flush=True)
        last = None
        for step in range(start, args.steps):
            heartbeat_tick()
            # the schedule's kill-at-step fires here (fault-state marker
            # keeps the RESTARTED incarnation from re-firing it)
            faults.fire("train.step", step=step)
            last = float(exe.run(main, feed=feed, fetch_list=[loss])[0][0])
            # blocking saves: the chaos timeline must be exact, not racing
            # an async writer
            ck.maybe_save(step, blocking=True)
            if args.step_sleep:
                time.sleep(args.step_sleep)
        ck.close()
        final = {
            v.name: np.asarray(scope.find_var(v.name))
            for v in main.global_block().vars.values()
            if v.persistable and scope.find_var(v.name) is not None
        }
    os.makedirs(args.out, exist_ok=True)
    np.savez(os.path.join(args.out, f"final_rank{rank}.npz"), **final)
    print(f"CHAOS_RESULT rank={rank} steps={args.steps} loss={last}",
          flush=True)
    return 0


# ---------------------------------------------------------------------------
# supervisor: the chaos scenario driver
# ---------------------------------------------------------------------------


def run_supervisor(args):
    import numpy as np

    from paddle_tpu.incubate.checkpoint import newest_valid_checkpoint
    from paddle_tpu.resilience import corrupt_file
    from paddle_tpu.resilience.supervisor import GangSupervisor

    work = args.workdir or tempfile.mkdtemp(prefix="chaos_train_")
    ckpt_base = os.path.join(work, "ckpt")
    out_dir = os.path.join(work, "out")
    ref_ckpt = os.path.join(work, "ref_ckpt")
    ref_out = os.path.join(work, "ref_out")
    fault_state = os.path.join(work, "fault_state")
    os.makedirs(ckpt_base, exist_ok=True)

    schedule = [{
        "site": "train.step", "action": "kill", "at_step": args.kill_step,
        "rank": args.kill_rank, "exit_code": 43, "id": "chaos-kill",
    }]
    worker_args = [
        os.path.abspath(__file__), "--worker",
        "--steps", str(args.steps), "--interval", str(args.interval),
        "--feat", str(args.feat), "--step-sleep", str(args.step_sleep),
        "--ckpt-base", ckpt_base, "--out", out_dir,
    ]

    corrupted = {}

    def sabotage(attempt, events):
        """Before the first relaunch: corrupt rank 0's newest checkpoint
        (fault (b)), then snapshot the dir — the reference run resumes
        from this exact state."""
        if attempt != 1:
            return
        r0 = os.path.join(ckpt_base, "rank0")
        name = newest_valid_checkpoint(r0, quarantine=False)
        if name is None:
            return
        corrupt_file(os.path.join(r0, name, "state.npz"))
        corrupted["name"] = name
        shutil.copytree(r0, ref_ckpt)

    sup = GangSupervisor(
        worker_args, nproc=args.nproc, max_restarts=args.max_restarts,
        restart_backoff_s=0.2,
        hang_timeout_s=args.hang_timeout,
        checkpoint_dirs=[os.path.join(ckpt_base, f"rank{r}")
                         for r in range(args.nproc)],
        on_restart=sabotage,
        extra_env={
            "PADDLE_TPU_FAULTS": json.dumps(schedule),
            "PADDLE_TPU_FAULT_STATE": fault_state,
        },
    )
    t0 = time.perf_counter()
    codes = sup.run()
    wall = time.perf_counter() - t0

    kills = [e for e in sup.events
             if e["kind"] == "rank_exit" and e["code"] == 43]
    quarantined = [n for n in os.listdir(os.path.join(ckpt_base, "rank0"))
                   if ".corrupt" in n]

    # -- reference: uninterrupted run resumed from the same checkpoint ----
    env = {k: v for k, v in os.environ.items()
           if k not in ("PADDLE_TPU_FAULTS", "PADDLE_TPU_FAULT_STATE")}
    env["PADDLE_TRAINER_ID"] = "0"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    # reference resumes from the snapshot taken right after corruption
    ref_ckpt_base = os.path.join(work, "ref_ckpt_base")
    os.makedirs(ref_ckpt_base, exist_ok=True)
    shutil.copytree(ref_ckpt, os.path.join(ref_ckpt_base, "rank0"))
    ref = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--worker",
         "--steps", str(args.steps), "--interval", str(args.interval),
         "--feat", str(args.feat), "--step-sleep", "0",
         "--ckpt-base", ref_ckpt_base, "--out", ref_out],
        env=env, capture_output=True, text=True, timeout=300,
    )
    assert ref.returncode == 0, ref.stdout[-2000:] + ref.stderr[-2000:]

    got = np.load(os.path.join(out_dir, "final_rank0.npz"))
    want = np.load(os.path.join(ref_out, "final_rank0.npz"))
    assert sorted(got.files) == sorted(want.files), (got.files, want.files)
    bit_identical = all(
        got[n].dtype == want[n].dtype and np.array_equal(got[n], want[n])
        for n in got.files
    )

    report = {
        "metric": "chaos_train_recovery",
        "value": sup.restarts,
        "unit": "restarts",
        "extra": {
            "codes": codes,
            "wall_s": round(wall, 2),
            "injected_kills": len(kills),
            "corrupted_checkpoint": corrupted.get("name"),
            "quarantined": quarantined,
            "restarts": sup.restarts,
            "bit_identical_to_reference": bit_identical,
            "events": [
                {k: v for k, v in e.items() if k != "time"}
                for e in sup.events
            ],
        },
    }
    print(json.dumps(report))
    assert all(c == 0 for c in codes), codes
    assert kills, "the kill fault never fired"
    assert sup.restarts >= 1, "gang never restarted"
    assert corrupted.get("name"), "no checkpoint was corrupted"
    assert quarantined, "corrupt checkpoint was not quarantined on resume"
    assert bit_identical, (
        "recovered run diverged from the uninterrupted reference"
    )
    print(f"CHAOS_OK restarts={sup.restarts} wall={wall:.1f}s")
    if not args.workdir:
        shutil.rmtree(work, ignore_errors=True)
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--worker", action="store_true",
                    help="internal: run as one training rank")
    ap.add_argument("--nproc", type=int, default=2)
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--interval", type=int, default=5)
    ap.add_argument("--feat", type=int, default=8)
    ap.add_argument("--kill-step", type=int, default=12)
    ap.add_argument("--kill-rank", type=int, default=1)
    ap.add_argument("--max-restarts", type=int, default=2)
    ap.add_argument("--hang-timeout", type=float, default=None)
    ap.add_argument("--step-sleep", type=float, default=0.05,
                    help="per-step sleep so kills land mid-gang")
    ap.add_argument("--ckpt-base", type=str, default=None)
    ap.add_argument("--out", type=str, default=None)
    ap.add_argument("--workdir", type=str, default=None,
                    help="keep artifacts here instead of a tmpdir")
    ap.add_argument("--smoke", action="store_true",
                    help="seconds-scale run + invariant asserts (CI)")
    args = ap.parse_args(argv)
    if args.smoke:
        args.nproc, args.steps, args.interval = 2, 8, 2
        args.kill_step, args.kill_rank, args.max_restarts = 5, 1, 2
    if args.worker:
        return run_worker(args)
    return run_supervisor(args)


if __name__ == "__main__":
    sys.exit(main())
