#!/usr/bin/env python
"""Deterministic-interleaving concurrency stress harness + evidence.

Drives the repo's hottest threaded paths — GenerationEngine
admission/retire, RequestQueue admission/expiry, EmbeddingEngine
write-back, and the dataio pipeline — under SEEDED stall injection at
lock boundaries, with the runtime lockdep witness armed. Two seams
perturb thread interleavings:

  * the lockdep stall hook: whether acquisition #n of lock class L
    stalls (and for how long) is a pure function of (seed, L, n) —
    replaying a seed replays the exact stall schedule;
  * ``resilience.faults`` stall rules at the existing sites
    (decode.step/prefill/inject/sample/spill/resume, lookup.pull/push,
    dataio.read)
    with per-rule seeded probability.

Every scenario asserts a BIT-EXACT property against an unstressed
serial reference (decode tokens == offline decode, embedding host tier
== reference run, dataio stream digest == worker-count-0 digest) plus
counter-consistency invariants — so "the schedule changed the answer"
is a failure, not noise. A failing seed replays with::

    python tools/stress_concurrency.py --scenario decode --seed 17

CI contract: exit 0 = clean, 1 = failures, 2 = internal error;
``--smoke`` runs every scenario once on the default seed (wired into
tier-1 by tests/test_concurrency.py); ``--json`` machine summary.

``--evidence OUT.json`` regenerates CONCURRENCY_EVIDENCE_r11.json: a
DETERMINISTIC single-threaded lockdep pass over the decode + serving +
embedding + checkpoint + dataio drivers records the discovered
lock-order hierarchy (e.g. ``serving.queue -> decode.tenant``), merged
with the static lint inventory — drift-gated by
tests/test_concurrency.py (runtime half) and
``tools/lint_concurrency.py --smoke`` (static half).
"""

import argparse
import hashlib
import json
import os
import random
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
EXIT_CLEAN, EXIT_FINDINGS, EXIT_INTERNAL = 0, 1, 2

os.environ.setdefault("JAX_PLATFORMS", "cpu")

SCENARIOS = ("queue", "decode", "embedding", "dataio")


class StallSchedule:
    """Deterministic stall decisions at lock boundaries. Install with
    ``lockdep.set_stall_hook(schedule)``; every enabled acquisition of
    lock class L consults ``(seed, L, n)`` — no wall clock, no thread
    identity — so one seed IS one schedule."""

    def __init__(self, seed, prob=0.2, delay_s=0.002):
        self.seed = int(seed)
        self.prob = float(prob)
        self.delay_s = float(delay_s)
        self._mu = threading.Lock()  # hook runs on every scenario thread
        self._stalls = 0

    @property
    def stalls(self):
        with self._mu:
            return self._stalls

    def __call__(self, name, n):
        r = random.Random(f"{self.seed}:{name}:{n}").random()
        if r < self.prob:
            with self._mu:
                self._stalls += 1
            time.sleep(self.delay_s)


def _stall_rules(seed, sites, prob=0.35, delay_s=0.003):
    return [{"site": s, "action": "stall", "delay_s": delay_s,
             "prob": prob, "seed": seed + i, "times": -1}
            for i, s in enumerate(sites)]


# ---------------------------------------------------------------------------
# scenario: RequestQueue admission / expiry / stats under contention
# ---------------------------------------------------------------------------


def scenario_queue(seed, n_per_thread=60, threads=4):
    from paddle_tpu.serving.decode.engine import GenerationRequest
    from paddle_tpu.serving.queue import RequestQueue
    from paddle_tpu.serving.request import Priority, RejectedError

    q = RequestQueue(max_depth=48)
    errors = []
    admitted = [0] * threads
    rejected = [0] * threads
    removed = [0]
    stop = threading.Event()

    def submitter(k):
        rng = random.Random((seed, "submit", k))
        try:
            for i in range(n_per_thread):
                deadline = (time.perf_counter() + 0.005
                            if rng.random() < 0.3 else None)
                req = GenerationRequest(
                    k * 10_000 + i, [1], 4, f"t{k % 2}",
                    rng.choice(Priority.LANES), deadline)
                try:
                    q.put(req)
                    admitted[k] += 1
                except RejectedError:
                    rejected[k] += 1
                if rng.random() < 0.2:
                    time.sleep(0.0005)
        except BaseException as e:
            errors.append(e)

    def reaper():
        try:
            while not stop.is_set():
                q.expire()
                with q.lock:
                    head = q.head()
                    if head is not None:
                        q.remove([head])
                        removed[0] += 1
                q.stats()
                q.lane_depths()
                time.sleep(0.0005)
        except BaseException as e:
            errors.append(e)

    ts = [threading.Thread(target=submitter, args=(k,), daemon=True)
          for k in range(threads)]
    rp = threading.Thread(target=reaper, daemon=True)
    rp.start()
    for t in ts:
        t.start()
    for t in ts:
        t.join(30)
    # drain what's left, then stop the reaper
    deadline = time.time() + 10
    while not q.empty() and time.time() < deadline:
        q.expire()
        with q.lock:
            head = q.head()
            if head is not None:
                q.remove([head])
                removed[0] += 1
    stop.set()
    rp.join(10)
    assert not errors, f"queue scenario raised: {errors[:3]}"
    st = q.stats()
    assert q.empty() and st["depth"] == 0, st
    # conservation: every admitted row left via remove or expiry
    total_admitted = sum(admitted)
    accounted = removed[0] + st["expired_in_queue"]
    assert accounted == total_admitted, (
        f"row accounting broke: admitted {total_admitted} != removed "
        f"{removed[0]} + expired {st['expired_in_queue']}")
    assert st["rejected_at_admission"] == sum(rejected)
    return {"admitted": total_admitted, "removed": removed[0],
            "expired": st["expired_in_queue"], "rejected": sum(rejected)}


# ---------------------------------------------------------------------------
# scenario: continuous-batching decode vs offline reference
# ---------------------------------------------------------------------------


def _small_decode_model(name, slots=2, max_len=10, **kw):
    from paddle_tpu.serving.decode import build_decoder_model

    return build_decoder_model(
        vocab_size=16, hidden=8, num_layers=1, slots=slots,
        max_len=max_len, eos_id=None, name=name, version="1", **kw,
    )


def scenario_decode(seed, n_requests=6):
    from paddle_tpu.resilience import faults
    from paddle_tpu.serving.decode import (
        BeamParams,
        GenerationEngine,
        SamplingParams,
    )

    rng = random.Random((seed, "decode"))
    prompts = [[rng.randrange(16) for _ in range(rng.randrange(1, 5))]
               for _ in range(n_requests)]
    max_news = [rng.randrange(1, 5) for _ in range(n_requests)]
    # odd requests run the r17 committed-sampling policy: the stream is
    # keyed per (seed, emitted-index), so the stall schedule must not be
    # able to change a single byte of it
    samplings = [SamplingParams(temperature=0.8, top_k=6, seed=seed + i)
                 if i % 2 else None for i in range(n_requests)]

    engine = GenerationEngine(queue_depth=32, breaker_threshold=0)
    engine.set_tenant("a", weight=2.0)
    engine.set_tenant("b", weight=1.0, max_in_flight=1)
    entry = engine.register_model(
        lambda: _small_decode_model(f"stress{seed}"))
    refs = [entry.offline_decode(p, n, sampling=sp)
            for p, n, sp in zip(prompts, max_news, samplings)]
    beam_ref = entry.offline_beam(prompts[0], 3, BeamParams(2))

    faults.configure(_stall_rules(
        seed, ["decode.step", "decode.prefill", "decode.inject",
               "decode.sample", "decode.spill", "decode.resume"]))
    try:
        engine.start()
        resps = {}
        errors = []

        def submit_half(k):
            try:
                for i in range(k, n_requests, 2):
                    resps[i] = engine.submit(
                        prompts[i], max_new_tokens=max_news[i],
                        sampling=samplings[i],
                        tenant="a" if i % 3 else "b")
                    time.sleep(0.001 * ((seed + i) % 3))
            except BaseException as e:
                errors.append(e)

        ts = [threading.Thread(target=submit_half, args=(k,), daemon=True)
              for k in (0, 1)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(60)
        assert not errors, f"decode submit raised: {errors[:3]}"
        for i, resp in resps.items():
            got = [int(t) for t in resp.result(timeout=120)["tokens"]]
            assert got == refs[i], (
                f"seed {seed} request {i}: continuous {got} != offline "
                f"{refs[i]} — schedule changed the answer")
        # COW beam search under the same stall schedule: ranked
        # hypotheses byte-equal the offline reference, pool conserved
        beam = engine.submit(prompts[0], max_new_tokens=3,
                             beam_width=2).result(timeout=120)
        got_beams = [[int(t) for t in h["tokens"]] for h in beam["beams"]]
        assert got_beams == [list(rt) for rt, _rs in beam_ref], (
            f"seed {seed} beam: {got_beams} != {beam_ref}")
        entry.block_pool.check_conservation()
    finally:
        faults.reset()
        engine.shutdown()
    st = entry.stats()
    assert st["completed"] == n_requests + 1, st["completed"]
    assert st["failed"] == 0 and st["step_failures"] == 0
    assert st["sampled_tokens"] > 0
    overload = _decode_overload_leg(seed)
    return {"requests": n_requests + 1,
            "decode_steps": st["decode_steps"],
            "sampled_tokens": st["sampled_tokens"],
            "beam_forks": st["beam_forks"],
            "occupancy": round(st["occupancy"], 3),
            "parked": overload["parked"],
            "resumed": overload["resumed"]}


def _decode_overload_leg(seed):
    """r18 preemption under the stall schedule: an undersized block
    pool forces one of two in-flight sessions to park (KV rows spill to
    the host tier through decode.spill) and resume (decode.resume) —
    stalls inside the spill/re-inject window must not change a byte of
    either stream."""
    from paddle_tpu.resilience import faults
    from paddle_tpu.serving.decode import GenerationEngine

    rng = random.Random((seed, "overload"))
    prompts = [[rng.randrange(16) for _ in range(4)] for _ in range(2)]
    engine = GenerationEngine(queue_depth=8, breaker_threshold=0)
    entry = engine.register_model(lambda: _small_decode_model(
        f"stress_ov{seed}", slots=2, max_len=16, block_size=2,
        num_blocks=6))
    refs = [entry.offline_decode(p, 6) for p in prompts]
    faults.configure(_stall_rules(
        seed, ["decode.step", "decode.prefill", "decode.inject",
               "decode.sample", "decode.spill", "decode.resume"]))
    try:
        engine.start()
        resps = [engine.submit(p, max_new_tokens=6) for p in prompts]
        for i, resp in enumerate(resps):
            got = [int(t) for t in resp.result(timeout=120)["tokens"]]
            assert got == refs[i], (
                f"seed {seed} overload request {i}: {got} != {refs[i]} "
                f"— a spill/resume interleaving changed the answer")
        entry.block_pool.check_conservation()
    finally:
        faults.reset()
        engine.shutdown()
    st = entry.stats()
    # both prompts decode to 10 tokens against a 12-row pool: mid-gen
    # exhaustion parks (never fails) — the pool CAN fit each alone
    assert st["sessions_parked"] >= 1 and st["sessions_resumed"] >= 1, st
    assert st["failed"] == 0, st
    assert st["host_tier"]["spills"] >= 1, st["host_tier"]
    return {"parked": st["sessions_parked"],
            "resumed": st["sessions_resumed"]}


# ---------------------------------------------------------------------------
# scenario: embedding write-back vs serial reference (bit-exact tiers)
# ---------------------------------------------------------------------------


def _embedding_stream(seed, steps=30, batch=6, id_space=40):
    rng = random.Random((seed, "embedding"))
    return [[rng.randrange(id_space) for _ in range(batch)]
            for _ in range(steps)]


def _run_embedding(seed, stream, stressed):
    import numpy as np

    import paddle_tpu as fluid
    from paddle_tpu.embedding.store import EmbeddingEngine
    from paddle_tpu.embedding.table import TableConfig
    from paddle_tpu.resilience import faults

    scope = fluid.Scope()
    engine = EmbeddingEngine(scope=scope, push_workers=2)
    rt = engine.register(TableConfig(f"stress{seed}", 4, capacity=16, ep=2))
    stop = threading.Event()
    errors = []

    def poller():
        try:
            while not stop.is_set():
                rt.stats()
                len(rt.store)
                time.sleep(0.0005)
        except BaseException as e:
            errors.append(e)

    if stressed:
        faults.configure(_stall_rules(seed, ["lookup.pull", "lookup.push"]))
        th = threading.Thread(target=poller, daemon=True)
        th.start()
    try:
        for step, ids in enumerate(stream):
            arr = np.asarray(ids, dtype=np.int64)
            slots, _inv = rt.lookup(arr, train=True)
            # simulated train update: a pure function of (id, step), so
            # the final host tier is schedule-independent by contract
            slab = np.array(rt.slab_host())
            for idv in sorted(set(ids)):
                slab[rt._slot[idv]] += np.float32(
                    ((idv * 31 + step) % 7) * 0.125)
            scope.set(rt.cfg.slab_name, slab)
        engine.flush()
    finally:
        if stressed:
            stop.set()
            th.join(10)
            faults.reset()
        engine.close()
    assert not errors, f"embedding poller raised: {errors[:3]}"
    return rt.store.snapshot_blocks()


def scenario_embedding(seed):
    import numpy as np

    stream = _embedding_stream(seed)
    ref = _run_embedding(seed, stream, stressed=False)
    got = _run_embedding(seed, stream, stressed=True)
    assert len(ref) == len(got)
    rows = 0
    for (rid, rrow), (gid, grow) in zip(ref, got):
        assert np.array_equal(rid, gid), "host-tier id sets diverged"
        assert np.array_equal(rrow, grow), (
            f"seed {seed}: write-back order changed row VALUES — the "
            f"stale-read/marker contract is broken")
        rows += len(rid)
    return {"steps": len(stream), "host_rows": rows}


# ---------------------------------------------------------------------------
# scenario: dataio pipeline determinism under read stalls
# ---------------------------------------------------------------------------


def _dataio_digest(seed, num_workers, prefetch):
    import numpy as np

    from paddle_tpu.dataio.engine import DataEngine
    from paddle_tpu.dataio.prefetch import DevicePrefetcher
    from paddle_tpu.dataio.source import ListSource

    def transform(item, rng):
        return np.asarray([item * 3 + 1, rng.randrange(1000)],
                          dtype=np.int64)

    engine = DataEngine(
        ListSource(list(range(96)), seed=seed), transform=transform,
        batch_size=8, num_workers=num_workers, name=f"stress{seed}",
    )
    it = DevicePrefetcher(engine, depth=2) if prefetch else engine
    h = hashlib.sha256()
    for batch in it:
        # canonical int64 view: device placement narrows to int32 under
        # jax's default x64-off config — a dtype artifact, not a stream
        # property, so the digest compares VALUES
        h.update(np.ascontiguousarray(
            np.asarray(batch, dtype=np.int64)).tobytes())
    return h.hexdigest()


def scenario_dataio(seed):
    from paddle_tpu.resilience import faults

    ref = _dataio_digest(seed, num_workers=0, prefetch=False)
    faults.configure(_stall_rules(seed, ["dataio.read"], prob=0.3,
                                  delay_s=0.002))
    try:
        got = _dataio_digest(seed, num_workers=3, prefetch=True)
    finally:
        faults.reset()
    assert got == ref, (
        f"seed {seed}: dataio stream digest {got[:12]} != serial "
        f"reference {ref[:12]} — worker timing leaked into the stream")
    return {"digest": ref[:12]}


_SCENARIO_FNS = {
    "queue": scenario_queue,
    "decode": scenario_decode,
    "embedding": scenario_embedding,
    "dataio": scenario_dataio,
}


# ---------------------------------------------------------------------------
# deterministic evidence drivers (single-threaded lockdep pass)
# ---------------------------------------------------------------------------


def _drive_decode_evidence():
    """Decode + serving-queue exercise with NO scheduler thread: submit,
    expire, admit (prefill+inject), step, retire — every acquisition on
    this thread, so the discovered edge set is a pure function of the
    code."""
    from paddle_tpu.serving.decode import GenerationEngine

    engine = GenerationEngine(queue_depth=16, breaker_threshold=0)
    engine.set_tenant("a", weight=2.0)
    entry = engine.register_model(
        lambda: _small_decode_model("evidence", slots=2, max_len=8))
    r1 = engine.submit([1, 2], max_new_tokens=2, tenant="a")
    r2 = engine.submit([3], max_new_tokens=2, tenant="b")
    dead = engine.submit([4], max_new_tokens=2, tenant="a",
                         deadline_ms=0.001)
    time.sleep(0.002)
    with entry._cond:
        for r in entry._queue.expire():
            entry._reject_expired(r)
    entry._admit_free_slots()
    for _ in range(4):
        entry._step()
    assert r1.done() and r2.done() and dead.done()
    assert entry.stats()["completed"] == 2
    # r17 generation modes on this same thread: beam fork/prune walks
    # blocks-under-slot chains, draft-KV walks decode.draft ->
    # decode.blocks (the declared proposal-slot chain)
    engine.register_model(
        lambda: _small_decode_model("evidence_d", slots=2, max_len=8))
    b = engine.submit([1, 2], max_new_tokens=2, model="evidence",
                      beam_width=2)
    s = engine.submit([3, 1], max_new_tokens=2, model="evidence",
                      draft_model="evidence_d", spec_k=2)
    for _ in range(12):
        if b.done() and s.done():
            break
        entry._iterate()
    assert b.done() and s.done()
    entry.block_pool.check_conservation()
    # r18 overload on this same thread: an undersized pool parks one of
    # two in-flight sessions — the spill write-back runs tier.put under
    # decode.blocks, witnessing the declared decode.blocks ->
    # decode.tier edge; the resume walks it again via the host tier
    ov = engine.register_model(
        lambda: _small_decode_model("evidence_ov", slots=2, max_len=16,
                                    block_size=2, num_blocks=6))
    o1 = engine.submit([1, 2, 3, 4], max_new_tokens=6,
                       model="evidence_ov")
    o2 = engine.submit([5, 6, 7, 8], max_new_tokens=6,
                       model="evidence_ov")
    for _ in range(40):
        if o1.done() and o2.done():
            break
        ov._iterate()
    assert o1.done() and o2.done()
    assert o1.error() is None and o2.error() is None
    ost = ov.stats()
    assert ost["sessions_parked"] >= 1 and ost["sessions_resumed"] >= 1
    ov.block_pool.check_conservation()
    engine.stats()


def _drive_queue_evidence():
    from paddle_tpu.serving.decode.engine import GenerationRequest
    from paddle_tpu.serving.queue import RequestQueue
    from paddle_tpu.serving.request import Priority

    q = RequestQueue(max_depth=8)
    for i in range(4):
        q.put(GenerationRequest(i, [1], 2, "t", Priority.NORMAL, None))
    q.stats()          # re-entrant lane_depths under the RLock
    q.expire()
    with q.lock:
        head = q.head()
        q.remove([head])
    q.note_drained()


def _drive_embedding_evidence(tmpdir):
    """Embedding write-back + a checkpoint save through extra_state: the
    manifest/table/pending hierarchy in one deterministic pass."""
    import numpy as np

    import paddle_tpu as fluid
    from paddle_tpu.embedding.store import EmbeddingEngine
    from paddle_tpu.embedding.table import TableConfig
    from paddle_tpu.incubate.checkpoint import AutoCheckpoint

    scope = fluid.Scope()
    engine = EmbeddingEngine(scope=scope, push_workers=1)
    rt = engine.register(TableConfig("evidence", 4, capacity=16, ep=2))
    for step in range(6):
        ids = np.asarray([(step * 5 + j) % 24 for j in range(6)], np.int64)
        rt.lookup(ids, train=True)
    ckpt = AutoCheckpoint(None, fluid.Program(), tmpdir,
                          save_interval_steps=1, scope=scope,
                          extra_state=engine)
    ckpt.save(0, blocking=True)
    ckpt.close()
    engine.flush()
    engine.close()


def _drive_metrics_evidence():
    from paddle_tpu.observability import metrics as obs_metrics
    from paddle_tpu.serving.metrics import ServingMetrics

    m = ServingMetrics(engine_label="lockdep-evidence")
    m.tenant_incr("tokens", "a")
    m.tenant_counts("tokens")
    obs_metrics.scrape_text()


def _drive_dataio_evidence():
    _dataio_digest(0, num_workers=2, prefetch=True)


def _drive_fleet_evidence():
    """Fleet router + local replicas with NO pump or scheduler threads:
    submit (routing reads the replica queue depth under fleet.router —
    the hierarchy's top edge), a replica death, the parked re-dispatch,
    a hand-stepped completion, and the delivering tick — every
    acquisition on this thread."""
    from paddle_tpu.serving.decode import GenerationEngine
    from paddle_tpu.serving.fleet import FleetRouter, LocalReplica

    router = FleetRouter(health_interval_s=0.0)  # health pass each tick
    for i in range(2):
        engine = GenerationEngine(queue_depth=8, breaker_threshold=0,
                                  label=f"lockdep-fleet-{i}")
        engine.register_model(
            lambda: _small_decode_model("evidence", slots=2, max_len=8))
        router.add_replica(LocalReplica(f"r{i}", i, engine))
    resp = router.submit([1, 2], max_new_tokens=1)
    (rr,) = router._inflight.values()
    victim = rr.replica
    router._replicas[victim].kill()
    router._mark_dead(victim, "evidence")
    router._tick()          # health pass + re-dispatch of the parked rr
    assert rr.replica is not None and rr.replica != victim
    entry = router._replicas[rr.replica].engine.entry("evidence", "1")
    entry._admit_free_slots()   # prefill fast path finishes max_new=1
    router._tick()              # poll + deliver
    assert resp.done() and resp.error() is None
    router.stats()


def evidence_sections(tmpdir=None):
    """Run every deterministic driver under an armed, reset lockdep and
    return the evidence payload {lockdep, static}. The SAME function
    backs ``--evidence`` and the drift gate in tests/test_concurrency.py
    — committed claims must re-derive, byte-for-byte."""
    import importlib.util
    import tempfile

    from paddle_tpu.analysis.concurrency import scan_paths
    from paddle_tpu.observability import lockdep

    spec = importlib.util.spec_from_file_location(
        "lint_concurrency", os.path.join(REPO, "tools",
                                         "lint_concurrency.py"))
    lint_mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(lint_mod)

    was = lockdep.enabled()
    hook = lockdep.get_stall_hook()
    own_tmp = None
    if tmpdir is None:
        own_tmp = tempfile.TemporaryDirectory(prefix="lockdep_evidence_")
        tmpdir = own_tmp.name
    try:
        lockdep.set_stall_hook(None)
        lockdep.enable()
        lockdep.reset()
        _drive_queue_evidence()
        _drive_decode_evidence()
        _drive_embedding_evidence(tmpdir)
        _drive_metrics_evidence()
        _drive_dataio_evidence()
        _drive_fleet_evidence()
        snap = lockdep.snapshot()
    finally:
        lockdep.reset()
        lockdep.enable(was)
        lockdep.set_stall_hook(hook)
        if own_tmp is not None:
            own_tmp.cleanup()
    static = lint_mod.static_section(scan_paths([os.path.join(
        REPO, "paddle_tpu")]))
    return {
        "lockdep": {
            "edges": snap["edges"],
            "declared": sorted(snap["declared"]),
            "cycles": snap["cycles"],
            "violations": snap["violations"],
        },
        "static": static,
    }


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def _run_scenarios(names, seed, as_json):
    import logging

    from paddle_tpu.observability import lockdep

    # injected stalls are the POINT here — one warning per stall would
    # drown the scenario summaries
    logging.getLogger("paddle_tpu.resilience.faults").setLevel(
        logging.ERROR)
    failures = []
    results = {}
    total_stalls = 0
    was = lockdep.enabled()
    try:
        lockdep.enable()
        for name in names:
            # FRESH witness state + stall schedule per scenario: the
            # (seed, lock, nth-acquisition) stall decisions must start
            # from zero so `--scenario X --seed N` replays exactly what
            # this scenario saw inside a --smoke run
            lockdep.reset()
            schedule = StallSchedule(seed)
            lockdep.set_stall_hook(schedule)
            t0 = time.perf_counter()
            try:
                results[name] = _SCENARIO_FNS[name](seed)
                results[name]["seconds"] = round(
                    time.perf_counter() - t0, 2)
                results[name]["stalls"] = schedule.stalls
                snap = lockdep.snapshot()
                if snap["cycles"] or snap["violations"]:
                    raise AssertionError(
                        f"lockdep reported cycles={snap['cycles']} "
                        f"violations={snap['violations']}")
                print(f"stress: {name} ok (seed {seed}): {results[name]}")
            # LockOrderError IS a finding (exit 1), not a harness error
            # (exit 2): the witness raising is the primary signal here
            except (AssertionError, lockdep.LockOrderError) as e:
                failures.append(f"{name}: {e}")
                print(f"STRESS FAIL {name} (replay: python tools/"
                      f"stress_concurrency.py --scenario {name} "
                      f"--seed {seed}): {e}", file=sys.stderr)
            total_stalls += schedule.stalls
    finally:
        lockdep.set_stall_hook(None)
        lockdep.reset()
        lockdep.enable(was)
        from paddle_tpu.resilience import faults

        faults.reset()
    if not failures:
        print(f"stress: all scenarios bit-exact under seed {seed} "
              f"({total_stalls} lock-boundary stalls injected, "
              f"lockdep clean)")
    if as_json:
        print(json.dumps({"pass": not failures, "seed": seed,
                          "stalls": total_stalls,
                          "results": results, "failures": failures}))
    return EXIT_FINDINGS if failures else EXIT_CLEAN


def _write_evidence(path):
    payload = {
        "issue": 11,
        "generated_by": ("python tools/stress_concurrency.py --evidence "
                         "CONCURRENCY_EVIDENCE_r11.json"),
        "drift_gates": [
            "tests/test_concurrency.py::"
            "test_concurrency_evidence_r11_committed",
            "tools/lint_concurrency.py --smoke (static half)",
        ],
    }
    payload.update(evidence_sections())
    with open(path, "w") as f:
        json.dump(payload, f, indent=1, sort_keys=True)
        f.write("\n")
    lk = payload["lockdep"]
    print(f"wrote {path}: {len(lk['edges'])} witnessed edges, "
          f"{len(lk['declared'])} declared chains, cycles={lk['cycles']}, "
          f"{payload['static']['unsuppressed_findings']} static findings")
    return EXIT_CLEAN if not lk["cycles"] else EXIT_FINDINGS


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="deterministic concurrency stress harness")
    ap.add_argument("--scenario", choices=SCENARIOS, action="append",
                    help="run one scenario (repeatable; default all)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--smoke", action="store_true",
                    help="tier-1 gate: all scenarios once on the seed")
    ap.add_argument("--evidence", metavar="OUT.json",
                    help="regenerate the concurrency evidence file")
    ap.add_argument("--json", action="store_true", dest="as_json")
    try:
        args = ap.parse_args(argv)
        if args.evidence:
            return _write_evidence(args.evidence)
        if args.smoke and args.scenario:
            print("--smoke is the ALL-scenarios tier-1 gate; drop "
                  "--scenario (use --scenario/--seed alone to replay)",
                  file=sys.stderr)
            return EXIT_INTERNAL
        names = list(SCENARIOS) if args.smoke \
            else (args.scenario or list(SCENARIOS))
        return _run_scenarios(names, args.seed, args.as_json)
    except SystemExit as e:
        raise SystemExit(EXIT_INTERNAL if e.code not in (0, 1, 2)
                         else e.code)
    except Exception:
        import traceback

        traceback.print_exc()
        return EXIT_INTERNAL


if __name__ == "__main__":
    sys.exit(main())
