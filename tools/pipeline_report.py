#!/usr/bin/env python
"""PIPELINE_EVIDENCE_r20 generator: the pipeline runtime's claims, live.

Round 20's subsystem (paddle_tpu/parallel/pipeline_runtime/) claims

  1. schedule tables: compile_schedule emits collision-free slot tables
     whose REALIZED bubble (walking the table the runtime executes)
     matches the closed-form prediction, and interleaved 1F1B beats the
     GPipe bubble 3/7 at 4 stages x 4 microbatches,
  2. numerics: per-schedule training loss streams on a 4-stage mesh are
     BIT-IDENTICAL to the single-device no-pipeline reference (replicated
     feeds, microbatched fallback — same per-gemm shapes everywhere),
  3. caching: the schedule is compile-cache content — flipping
     gpipe<->1f1b on the same Program retraces, rerunning the identical
     config hits the in-memory tier (observed via lowering_jit_total),
  4. hierarchy: on a two-level DCN x ICI mesh the naive grad-sync
     all-reduce crosses DCN at exactly the statically predicted payload
     (replica-group parse of the optimized HLO), and the ZeRO-sharded
     placement that the decomposed analyzer events describe strictly
     reduces measured DCN-crossing bytes and silences the hierarchical
     linter.

tests/test_pipeline_runtime.py::test_pipeline_evidence_r20_committed
re-derives the static half byte-for-byte; the slow live gate re-runs the
training arms and compares the committed float-hex streams.

Usage: python tools/pipeline_report.py [--out PIPELINE_EVIDENCE_r20.json]
       python tools/pipeline_report.py --smoke   # static half only
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

EVIDENCE = "PIPELINE_EVIDENCE_r20.json"
STAGES = 4
MICROBATCHES = 4
INTERLEAVE = 2
LAYERS = 8
TRAIN_STEPS = 4
B, S, H = 8, 4, 16


def static_sections():
    """Schedule-table accounting — pure compile_schedule, no lowering.
    The committed evidence's static half; the drift test recomputes this
    byte-for-byte."""
    from paddle_tpu.parallel.pipeline_runtime.schedule import (
        compile_schedule,
    )

    schedules = {}
    for kind, v in (("gpipe", None), ("1f1b", INTERLEAVE)):
        sched = compile_schedule(kind, STAGES, MICROBATCHES, v)
        tab = sched.to_table()
        tab["fingerprint"] = sched.fingerprint()
        schedules[kind] = tab
    return {
        "geometry": {"stages": STAGES, "microbatches": MICROBATCHES,
                     "interleave": INTERLEAVE, "layers": LAYERS},
        "schedules": schedules,
        "claims": {
            "gpipe_bubble": schedules["gpipe"]["realized_bubble"],
            "1f1b_bubble": schedules["1f1b"]["realized_bubble"],
            "1f1b_beats_gpipe": (schedules["1f1b"]["realized_bubble"]
                                 < schedules["gpipe"]["realized_bubble"]),
            "realized_matches_predicted": all(
                t["realized_bubble"] == t["predicted_bubble"]
                for t in schedules.values()),
            # interleave buys bubble, NOT stash: same stash BYTES (slots
            # scale by v, per-chunk layers shrink by v)
            "stash_slots": {k: t["peak_stash_slots"]
                            for k, t in schedules.items()},
        },
    }


def _build_stack_model(schedule, interleave):
    import paddle_tpu as fluid

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.data("x", shape=[B, S, H])
        y = fluid.data("y", shape=[B, S, H])
        stack = fluid.layers.PipelinedStack(
            num_layers=LAYERS, num_microbatches=MICROBATCHES,
            schedule=schedule, interleave=interleave,
        )
        with stack.layer():
            h = stack.input(x)
            w = stack.layer_param([H, H])
            b = stack.layer_param([H], is_bias=True)
            stack.output(fluid.layers.relu(fluid.layers.elementwise_add(
                fluid.layers.matmul(h, w), b)))
        out = stack()
        loss = fluid.layers.mean(fluid.layers.square(
            fluid.layers.elementwise_sub(out, y)))
        fluid.optimizer.SGD(learning_rate=0.05).minimize(loss)
    return main, startup, loss, stack


def _deterministic_params(main):
    """Creation-order param values from a fixed seed — the streams must
    reproduce across processes, so init never comes from the startup
    RNG."""
    import numpy as np

    r = np.random.RandomState(7)
    return [r.randn(*p.shape).astype("float32") * 0.1
            for p in main.all_parameters()]


def _train_arm(schedule, interleave, on_mesh, steps=TRAIN_STEPS):
    import numpy as np

    import paddle_tpu as fluid
    from jax.sharding import PartitionSpec as P
    from paddle_tpu.parallel.env import make_mesh

    main, startup, loss, stack = _build_stack_model(schedule, interleave)
    rng = np.random.RandomState(3)
    feed = {"x": rng.randn(B, S, H).astype("float32"),
            "y": rng.randn(B, S, H).astype("float32")}
    prog = main
    if on_mesh:
        mesh = make_mesh((STAGES,), ("stage",))
        # replicated feeds: the loss mean must not be GSPMD-partitioned
        # or the reduction order diverges from the reference by ulps
        prog = fluid.CompiledProgram(main).with_parallel(
            mesh=mesh, loss_name=loss.name,
            input_specs={"x": P(), "y": P()},
            param_specs=stack.param_spec_overrides(),
        )
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        for p, val in zip(main.all_parameters(), _deterministic_params(main)):
            scope.set(p.name, val)
        return [
            float(np.asarray(
                exe.run(prog, feed=feed, fetch_list=[loss.name])[0]
            ).reshape(-1)[0])
            for _ in range(steps)
        ]


def training_section():
    """Live loss streams: ref (no mesh, microbatched fallback) vs gpipe
    vs interleaved 1f1b on the 4-stage mesh — committed as float hex so
    the gate is bitwise, not approximate."""
    ref = _train_arm("gpipe", None, on_mesh=False)
    gpipe = _train_arm("gpipe", None, on_mesh=True)
    f1b = _train_arm("1f1b", INTERLEAVE, on_mesh=True)
    return {
        "mesh": {"shape": [STAGES], "axes": ["stage"]},
        "steps": TRAIN_STEPS,
        "reference_loss_hex": [v.hex() for v in ref],
        "gpipe_loss_hex": [v.hex() for v in gpipe],
        "1f1b_loss_hex": [v.hex() for v in f1b],
        "reference_loss": ref,
        "gpipe_bit_identical": gpipe == ref,
        "1f1b_bit_identical": f1b == ref,
    }


def cache_section():
    """Schedule is compile-cache content: flip retraces, repeat hits."""
    import numpy as np

    import paddle_tpu as fluid
    from jax.sharding import PartitionSpec as P
    from paddle_tpu.observability import metrics as obs_metrics
    from paddle_tpu.parallel.env import make_mesh

    def jit_total():
        return obs_metrics.registry().get("lowering_jit_total").value

    main, startup, loss, stack = _build_stack_model("gpipe", None)
    rng = np.random.RandomState(3)
    feed = {"x": rng.randn(B, S, H).astype("float32"),
            "y": rng.randn(B, S, H).astype("float32")}
    exe = fluid.Executor(fluid.CPUPlace())
    mesh = make_mesh((STAGES,), ("stage",))

    def run(schedule, interleave):
        prog = fluid.CompiledProgram(main).with_parallel(
            mesh=mesh, loss_name=loss.name,
            input_specs={"x": P(), "y": P()},
            param_specs=stack.param_spec_overrides(),
            pipeline_schedule=schedule, pipeline_interleave=interleave,
        )
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe.run(startup)
            exe.run(prog, feed=feed, fetch_list=[loss.name])

    base = jit_total()
    run("gpipe", None)
    after_first = jit_total()
    run("1f1b", INTERLEAVE)
    after_flip = jit_total()
    run("1f1b", INTERLEAVE)
    after_repeat = jit_total()
    return {
        "jit_compiles": {"first": after_first - base,
                         "flip_to_1f1b": after_flip - after_first,
                         "repeat_1f1b": after_repeat - after_flip},
        "flip_retraces": after_flip > after_first,
        "repeat_hits_memory_tier": after_repeat == after_flip,
    }


def _build_mlp():
    import paddle_tpu as fluid

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.data("x", shape=[-1, 16])
        y = fluid.data("y", shape=[-1, 16])
        h = fluid.layers.fc(x, size=32, act="relu", name="mlp.fc1")
        p = fluid.layers.fc(h, size=16, name="mlp.fc2")
        loss = fluid.layers.mean(fluid.layers.square(
            fluid.layers.elementwise_sub(p, y)))
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    return main, startup, loss


def hierarchy_section():
    """Two-level DCN x ICI mesh, two arms: naive (replicated params, one
    flat grad-sync all-reduce spanning both tiers) and zero (params
    ZeRO-sharded over the ICI data axis, analyzer emits reduce-scatter
    over ICI + all-reduce of the shard over DCN). Gates: the naive arm's
    measured DCN-crossing HLO bytes equal the static prediction EXACTLY;
    the zero arm strictly reduces measured crossing bytes and the
    hierarchical linter is silent on its decomposed events."""
    import numpy as np

    import paddle_tpu as fluid
    from jax.sharding import PartitionSpec as P
    from paddle_tpu.analysis.cost import (
        analyze_cost,
        hierarchical_collective_diagnostics,
    )
    from paddle_tpu.analysis.sharding import analyze_sharding
    from paddle_tpu.parallel.env import make_mesh
    from paddle_tpu.parallel.pipeline_runtime.hierarchy import (
        dcn_crossing_collective_bytes,
    )
    from paddle_tpu.utils.hlo import lower_parallel_step

    mesh_shape, axes = (2, 4), ("dcn", "data")
    tags = {"dcn": "dcn", "data": "ici"}
    ispec = {"x": P(("dcn", "data")), "y": P(("dcn", "data"))}
    rng = np.random.RandomState(0)
    feed = {"x": rng.randn(16, 16).astype("float32"),
            "y": rng.randn(16, 16).astype("float32")}
    fs = {k: v.shape for k, v in feed.items()}

    out = {"mesh": {"shape": list(mesh_shape), "axes": list(axes)},
           "axis_tags": tags}
    for arm in ("naive", "zero"):
        main, startup, loss = _build_mlp()
        pspecs = None
        if arm == "zero":
            pspecs = {p.name: P("data") for p in main.all_parameters()}
        srep = analyze_sharding(
            main, make_mesh(mesh_shape, axes), param_specs=pspecs,
            input_specs=ispec, feed_shapes=fs)
        gs = [e for e in srep.events if e.cause == "grad-sync"]
        predicted_crossing = sum(e.bytes for e in gs if "dcn" in e.axes)
        crep = analyze_cost(
            main, mesh=make_mesh(mesh_shape, axes), axis_tags=tags,
            param_specs=pspecs, input_specs=ispec, feed_shapes=fs,
            fetch_names=[loss.name])
        linter = hierarchical_collective_diagnostics(crep)

        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe.run(startup)
            prog = fluid.CompiledProgram(main).with_parallel(
                mesh=make_mesh(mesh_shape, axes), loss_name=loss.name,
                param_specs=pspecs, input_specs=ispec)
            lowered, _mesh = lower_parallel_step(
                exe, prog, feed, [loss.name], scope)
        rep = dcn_crossing_collective_bytes(
            lowered.compile().as_text(), mesh_shape, axes, tags)
        out[arm] = {
            "grad_sync_events": [
                {"kind": e.kind, "var": e.var, "bytes": e.bytes,
                 "axes": sorted(e.axes)} for e in gs],
            "predicted_dcn_crossing_bytes": predicted_crossing,
            "measured_dcn_crossing_bytes": rep["crossing_bytes"],
            "measured_dcn_local_bytes": rep["local_bytes"],
            "linter_fired": len(linter),
            "linter_codes": sorted({d.code for d in linter}),
            "collectives": rep["collectives"],
        }
    naive, zero = out["naive"], out["zero"]
    out["claims"] = {
        "naive_exact_match": (naive["predicted_dcn_crossing_bytes"]
                              == naive["measured_dcn_crossing_bytes"]),
        "naive_linter_fired": naive["linter_fired"] > 0,
        "zero_linter_clean": zero["linter_fired"] == 0,
        "zero_reduces_crossing": (zero["measured_dcn_crossing_bytes"]
                                  < naive["measured_dcn_crossing_bytes"]),
        "measured_dcn_saving_bytes": (
            naive["measured_dcn_crossing_bytes"]
            - zero["measured_dcn_crossing_bytes"]),
    }
    return out


def build_report(smoke=False):
    report = {
        "generated_by": "tools/pipeline_report.py",
        "static": static_sections(),
    }
    if smoke:
        return report
    report["training"] = training_section()
    report["cache"] = cache_section()
    report["hierarchy"] = hierarchy_section()
    return report


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=EVIDENCE)
    ap.add_argument("--smoke", action="store_true",
                    help="static half only, compare against committed")
    args = ap.parse_args(argv)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

    if args.smoke:
        path = os.path.join(repo, EVIDENCE)
        with open(path) as f:
            committed = json.load(f)
        fresh = static_sections()
        if committed["static"] != fresh:
            print("pipeline evidence DRIFT: static half != committed — "
                  "regenerate with tools/pipeline_report.py")
            return 1
        print("pipeline evidence static half matches committed")
        return 0

    report = build_report()
    failures = []
    st = report["static"]["claims"]
    if not st["1f1b_beats_gpipe"]:
        failures.append("1f1b bubble does not beat gpipe")
    if not st["realized_matches_predicted"]:
        failures.append("realized bubble != closed-form prediction")
    tr = report["training"]
    if not (tr["gpipe_bit_identical"] and tr["1f1b_bit_identical"]):
        failures.append("loss streams not bit-identical to reference")
    ca = report["cache"]
    if not (ca["flip_retraces"] and ca["repeat_hits_memory_tier"]):
        failures.append("schedule flip/hit cache behavior wrong")
    hi = report["hierarchy"]["claims"]
    for k in ("naive_exact_match", "naive_linter_fired",
              "zero_linter_clean", "zero_reduces_crossing"):
        if not hi[k]:
            failures.append(f"hierarchy claim failed: {k}")
    report["pass"] = not failures
    report["failures"] = failures

    out_path = args.out if os.path.isabs(args.out) \
        else os.path.join(repo, args.out)
    with open(out_path, "w") as f:
        json.dump(report, f, indent=1, sort_keys=True)
        f.write("\n")
    print(f"wrote {out_path}: pass={report['pass']} "
          f"bubbles gpipe={st['gpipe_bubble']} 1f1b={st['1f1b_bubble']} "
          f"dcn saving={hi['measured_dcn_saving_bytes']}B")
    for msg in failures:
        print("FAIL:", msg)
    return 0 if report["pass"] else 1


if __name__ == "__main__":
    sys.exit(main())
