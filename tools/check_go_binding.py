"""Toolchain-free Go-binding cross-check.

The build image has no Go compiler and no network (recorded each round in
ROUND*_NOTES), so `go build` can never run here. This checker provides the
verification that IS possible: every `C.<symbol>` reference in go/ must
resolve against csrc/capi/paddle_tpu_capi.h — functions, typedefs, and enum
constants — so an ABI drift (renamed function, changed enum) fails the test
suite instead of waiting for a Go toolchain to notice.

Run: python tools/check_go_binding.py  (exit 0 = all symbols resolve)
"""

import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
HEADER = os.path.join(REPO, "csrc", "capi", "paddle_tpu_capi.h")

# cgo builtins that never come from the header
_CGO_BUILTINS = {
    "CString", "GoString", "GoStringN", "GoBytes", "CBytes", "free",
    "malloc", "int", "uint", "char", "uchar", "short", "ushort", "long",
    "ulong", "longlong", "ulonglong", "float", "double", "size_t",
    "int32_t", "int64_t", "uint8_t", "bool",
}


def _strip_comments(src):
    src = re.sub(r"/\*.*?\*/", " ", src, flags=re.S)
    return re.sub(r"//[^\n]*", " ", src)


def header_symbols():
    # comments stripped FIRST: a doc comment naming an old function must
    # not keep a renamed symbol "declared"
    src = _strip_comments(open(HEADER).read())
    syms = set()
    # typedefs, incl. pointer targets and multi-word base types:
    #   typedef struct PD_Foo PD_Foo;  typedef struct PD_Bar *PD_BarH;
    #   typedef unsigned char PD_Bool;  typedef const char *PD_Str;
    syms.update(re.findall(
        r"typedef\s+(?:[A-Za-z_]\w*\s+)+\*?\s*(\w+)\s*;", src
    ))
    # function-pointer typedefs: typedef void (*PD_Cb)(int);
    syms.update(re.findall(r"typedef[^;{]*\(\s*\*\s*(\w+)\s*\)", src))
    syms.update(re.findall(r"}\s*(\w+)\s*;", src))  # "} PD_Baz;"
    syms.update(re.findall(r"typedef\s+struct\s+(\w+)", src))
    # enum constants
    for body in re.findall(r"enum[^{]*{([^}]*)}", src, re.S):
        syms.update(re.findall(r"\b(PD_\w+)", body))
    # function declarations
    syms.update(re.findall(r"\b(PD_\w+)\s*\(", src))
    return syms


def go_references():
    refs = {}
    go_root = os.path.join(REPO, "go")
    for root, _dirs, files in os.walk(go_root):
        for fn in files:
            if not fn.endswith(".go"):
                continue
            path = os.path.join(root, fn)
            rel = os.path.relpath(path, go_root)
            # cgo comments are directives, not prose: scan the whole file
            for sym in re.findall(r"\bC\.(\w+)", open(path).read()):
                refs.setdefault(sym, []).append(rel)
    return refs


def main():
    syms = header_symbols()
    refs = go_references()
    if not refs:
        # zero references means the scan found nothing — a moved go/ dir
        # must fail the gate, not silently disable it
        print("ERROR: no C.<symbol> references found under go/ — "
              "binding sources missing or moved?")
        return 1
    missing = {
        s: files
        for s, files in sorted(refs.items())
        if s not in syms and s not in _CGO_BUILTINS
    }
    total = len(refs)
    if missing:
        print(f"UNRESOLVED {len(missing)}/{total} C symbols:")
        for s, files in missing.items():
            print(f"  C.{s}  (used in {', '.join(sorted(set(files)))})")
        return 1
    print(f"OK: all {total} C.<symbol> references resolve against "
          f"{os.path.relpath(HEADER, REPO)}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
