"""Perf-evidence report: structural metrics of the flagship train steps.

Prints one JSON object summarizing what tests/test_hlo.py asserts — S²
buffer count on the flash path, dot-operand dtype census, transpose count,
[S,V] logits check, ResNet conv dtype census, dp/tp collective counts.
The steps are lowered through the SAME shared builders the test gates use
(paddle_tpu/utils/hlo.py), so the committed evidence cannot drift from the
asserted computation. PROFILE.md links the committed snapshot.

Round 7 adds per-collective byte accounting (op kind x largest value the
collective materializes) for the SpecLayout-registry tp and dp x fsdp x tp
steps, with the MEGATRON_RULES lowering kept as the positive control —
the committed HLO_EVIDENCE_r07.json records that registry-placed steps
move ZERO full-parameter-shaped operands and stay activation-bounded
while the old rule table pays weight-sized gathers.

Usage: python tools/hlo_report.py [--out HLO_EVIDENCE_rNN.json]
       (~4 min on the CPU rig)
"""

import argparse
import json
import os
import sys
from collections import Counter

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from paddle_tpu.utils import hlo  # noqa: E402

S, VOCAB, P = 512, 30522, 77


def dot_census(txt):
    dots = hlo.stablehlo_dots(txt)
    c = Counter(
        (d[0].rsplit("x", 1)[-1], d[1].rsplit("x", 1)[-1]) for d in dots
    )
    return {f"{a}*{b}": n for (a, b), n in sorted(c.items())}


def main():
    from paddle_tpu.parallel.sharding import MEGATRON_RULES

    out = parse_args().out  # fail fast on bad args, before ~4 min of work
    report = {}
    flash = hlo.bert_train_step_text(
        True, seq_len=S, vocab=VOCAB, max_pred=P
    )
    tens = hlo.stablehlo_tensors(flash)
    report["bert_flash"] = {
        "s2_buffers": len(hlo.tensors_with_trailing(tens, (S, S))),
        "s_by_vocab_tensors": len(
            hlo.tensors_containing_dims(tens, (S, VOCAB))
        ),
        "dot_operand_dtypes": dot_census(flash),
        "transposes": flash.count("stablehlo.transpose"),
    }
    unfused = hlo.bert_train_step_text(
        False, seq_len=S, vocab=VOCAB, max_pred=P
    )
    report["bert_unfused_control"] = {
        "s2_buffers": len(
            hlo.tensors_with_trailing(hlo.stablehlo_tensors(unfused), (S, S))
        ),
    }
    report["resnet50_conv_dtypes"] = hlo.conv_dtype_census(
        hlo.resnet_train_step_text(depth=50, use_amp=True)
    )
    report["collectives_dp8"] = hlo.count_collectives(
        hlo.tiny_bert_parallel_text((8,), ("data",))
    )
    report["collectives_dp2_tp4"] = hlo.count_collectives(
        hlo.tiny_bert_parallel_text((2, 4), ("data", "model"),
                                    MEGATRON_RULES)
    )
    lowered, donated, _main = hlo.adam_mlp_step_lowered()
    report["adam_donation"] = {
        "donated_inputs": len(donated),
        "aliased_args": len(hlo.stablehlo_donated_args(lowered.as_text())),
        "unfused_adam_chain_ops": len(
            hlo.unfused_adam_chain_ops(lowered.compile().as_text())
        ),
    }
    report["spec_layout_r07"] = spec_layout_section()
    text = json.dumps(report, indent=1)
    print(text)
    if out:
        with open(out, "w") as f:
            f.write(text + "\n")


def spec_layout_section():
    """Collective byte accounting for the canonical-sharding-layer steps
    (what tests/test_hlo.py's registry gates assert, at the same
    collision-free geometry: seq 24 so no activation shape equals a
    parameter shape)."""
    from paddle_tpu.parallel.sharding import MEGATRON_RULES
    from paddle_tpu.parallel.spec_layout import SpecLayout

    geo = dict(seq_len=24, max_pred=20, with_param_shapes=True)
    sec = {"geometry": {"batch": 8, "seq_len": 24, "max_pred": 20}}

    def account(txt, shapes, tag):
        rep = hlo.collective_byte_report(txt)
        sec[f"collectives_{tag}"] = hlo.count_collectives(txt)
        sec[f"collective_bytes_{tag}"] = rep
        sec[f"weight_shaped_collectives_{tag}"] = len(
            hlo.weight_shaped_collectives(txt, shapes)
        )
        largest = 0
        for s in shapes:
            n = 4
            for d in s:
                n *= int(d)
            largest = max(largest, n)
        sec.setdefault("param_full_bytes", {
            "largest": largest,
            "shapes": sorted(list(s) for s in shapes),
        })

    txt, shapes = hlo.tiny_bert_parallel_text(
        (2, 4), ("data", "model"), spec_layout=SpecLayout(), **geo
    )
    account(txt, shapes, "tp_registry")
    txt, shapes = hlo.tiny_bert_parallel_text(
        (2, 2, 2), ("data", "fsdp", "model"), spec_layout=SpecLayout(),
        **geo
    )
    account(txt, shapes, "dp_fsdp_tp_registry")
    # positive control: the PR-4-era rule table still pays weight-sized
    # gathers for the params it leaves replicated — proves the detector
    txt, shapes = hlo.tiny_bert_parallel_text(
        (2, 4), ("data", "model"), param_rules=MEGATRON_RULES, **geo
    )
    account(txt, shapes, "megatron_control")
    return sec


def parse_args():
    p = argparse.ArgumentParser()
    p.add_argument("--out", default=None,
                   help="also write the JSON report to this path")
    return p.parse_args()


if __name__ == "__main__":
    main()
