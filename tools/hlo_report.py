"""Perf-evidence report: structural metrics of the flagship train steps.

Prints one JSON object summarizing what tests/test_hlo.py asserts — S²
buffer count on the flash path, dot-operand dtype census, transpose count,
[S,V] logits check, conv dtype census, dp/tp collective counts — so a
round's perf posture is inspectable without a chip (PROFILE.md links here).

Usage: python tools/hlo_report.py   (~4 min on the CPU rig)
"""

import json
import os
import sys
from collections import Counter

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

import paddle_tpu as fluid  # noqa: E402
from paddle_tpu.models import bert  # noqa: E402
from paddle_tpu.utils import hlo  # noqa: E402

S, VOCAB, P = 512, 30522, 77


def bert_step_text(flash):
    cfg = bert.BertConfig(
        vocab_size=VOCAB, hidden_size=768, num_hidden_layers=2,
        num_attention_heads=12, max_position_embeddings=S,
        use_flash_attention=flash,
        attention_probs_dropout_prob=0.0 if flash else 0.1,
    )
    main, startup, feeds, fetches = bert.build_bert_pretrain(
        cfg, seq_len=S, lr=1e-4, use_amp=True, max_predictions_per_seq=P
    )
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        data = bert.synthetic_batch(
            np.random.RandomState(0), 4, S, cfg, max_predictions_per_seq=P
        )
        return hlo.lower_program_step(
            main, data, [fetches[0]], scope=scope
        ).as_text()


def dot_census(txt):
    dots = hlo.stablehlo_dots(txt)
    c = Counter(
        (d[0].rsplit("x", 1)[-1], d[1].rsplit("x", 1)[-1]) for d in dots
    )
    return {f"{a}*{b}": n for (a, b), n in sorted(c.items())}


def main():
    report = {}
    flash = bert_step_text(flash=True)
    tens = hlo.stablehlo_tensors(flash)
    report["bert_flash"] = {
        "s2_buffers": len(hlo.tensors_with_trailing(tens, (S, S))),
        "s_by_vocab_tensors": len(
            hlo.tensors_containing_dims(tens, (S, VOCAB))
        ),
        "dot_operand_dtypes": dot_census(flash),
        "transposes": flash.count("stablehlo.transpose"),
    }
    unfused = bert_step_text(flash=False)
    report["bert_unfused_control"] = {
        "s2_buffers": len(
            hlo.tensors_with_trailing(hlo.stablehlo_tensors(unfused), (S, S))
        ),
    }

    from paddle_tpu.parallel.env import make_mesh
    from paddle_tpu.parallel.sharding import MEGATRON_RULES

    for name, shape, axes, rules in (
        ("dp8", (8,), ("data",), None),
        ("dp2_tp4", (2, 4), ("data", "model"), MEGATRON_RULES),
    ):
        cfg = bert.BertConfig.tiny()
        cfg.hidden_dropout_prob = 0.0
        cfg.attention_probs_dropout_prob = 0.0
        main, startup, feeds, fetches = bert.build_bert_pretrain(
            cfg, seq_len=16, lr=1e-3
        )
        exe = fluid.Executor(fluid.CPUPlace())
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe.run(startup)
            mesh = make_mesh(shape=shape, axis_names=axes)
            prog = fluid.CompiledProgram(main).with_parallel(
                mesh=mesh, loss_name=fetches[0].name, param_rules=rules
            )
            data = bert.synthetic_batch(np.random.RandomState(0), 8, 16, cfg)
            lowered, _ = hlo.lower_parallel_step(
                exe, prog, data, [fetches[0]], scope
            )
            report[f"collectives_{name}"] = hlo.count_collectives(
                lowered.compile().as_text()
            )
    print(json.dumps(report, indent=1))


if __name__ == "__main__":
    main()
