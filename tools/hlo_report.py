"""Perf-evidence report: structural metrics of the flagship train steps.

Prints one JSON object summarizing what tests/test_hlo.py asserts — S²
buffer count on the flash path, dot-operand dtype census, transpose count,
[S,V] logits check, ResNet conv dtype census, dp/tp collective counts.
The steps are lowered through the SAME shared builders the test gates use
(paddle_tpu/utils/hlo.py), so the committed evidence cannot drift from the
asserted computation. PROFILE.md links the committed snapshot.

Usage: python tools/hlo_report.py   (~4 min on the CPU rig)
"""

import json
import os
import sys
from collections import Counter

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from paddle_tpu.utils import hlo  # noqa: E402

S, VOCAB, P = 512, 30522, 77


def dot_census(txt):
    dots = hlo.stablehlo_dots(txt)
    c = Counter(
        (d[0].rsplit("x", 1)[-1], d[1].rsplit("x", 1)[-1]) for d in dots
    )
    return {f"{a}*{b}": n for (a, b), n in sorted(c.items())}


def main():
    from paddle_tpu.parallel.sharding import MEGATRON_RULES

    report = {}
    flash = hlo.bert_train_step_text(
        True, seq_len=S, vocab=VOCAB, max_pred=P
    )
    tens = hlo.stablehlo_tensors(flash)
    report["bert_flash"] = {
        "s2_buffers": len(hlo.tensors_with_trailing(tens, (S, S))),
        "s_by_vocab_tensors": len(
            hlo.tensors_containing_dims(tens, (S, VOCAB))
        ),
        "dot_operand_dtypes": dot_census(flash),
        "transposes": flash.count("stablehlo.transpose"),
    }
    unfused = hlo.bert_train_step_text(
        False, seq_len=S, vocab=VOCAB, max_pred=P
    )
    report["bert_unfused_control"] = {
        "s2_buffers": len(
            hlo.tensors_with_trailing(hlo.stablehlo_tensors(unfused), (S, S))
        ),
    }
    report["resnet50_conv_dtypes"] = hlo.conv_dtype_census(
        hlo.resnet_train_step_text(depth=50, use_amp=True)
    )
    report["collectives_dp8"] = hlo.count_collectives(
        hlo.tiny_bert_parallel_text((8,), ("data",))
    )
    report["collectives_dp2_tp4"] = hlo.count_collectives(
        hlo.tiny_bert_parallel_text((2, 4), ("data", "model"),
                                    MEGATRON_RULES)
    )
    lowered, donated, _main = hlo.adam_mlp_step_lowered()
    report["adam_donation"] = {
        "donated_inputs": len(donated),
        "aliased_args": len(hlo.stablehlo_donated_args(lowered.as_text())),
        "unfused_adam_chain_ops": len(
            hlo.unfused_adam_chain_ops(lowered.compile().as_text())
        ),
    }
    print(json.dumps(report, indent=1))


if __name__ == "__main__":
    main()
