#!/usr/bin/env python
"""Chaos harness for the fleet router: kill a replica mid-flight and
prove nothing was lost.

The scenario (the acceptance bar for the fleet subsystem, ROADMAP item
3's "tail latency p99 under kill-a-replica chaos" gate): an open-loop
request stream runs against a FleetRouter over N in-process decode
replicas; once the designated victim replica is holding live work, a
``replica.kill`` fault schedule is armed and the victim dies at its
next heartbeat. The router must (a) re-dispatch every request the dead
replica held — ZERO accepted-then-lost, (b) deliver every completed
generation BIT-IDENTICAL to the single-replica offline reference
(decode is deterministic, so failover is invisible in the bytes), (c)
replace the victim via autoscale with a replica that serves with ZERO
XLA traces (compile-cache warm pool), and (d) keep p99 degradation vs
the no-chaos baseline leg bounded.

``--smoke`` runs the seconds-scale configuration and asserts all of it
— wired into the fast tier by tests/test_fleet_serving.py, the same
pattern as tools/chaos_train.py. ``--evidence FLEET_EVIDENCE_r12.json``
writes the committed evidence file; its deterministic sections
(scenario config + invariants + the sha256 digest of every generated
token) are drift-gated by
tests/test_fleet_serving.py::test_fleet_evidence_r12_committed, which
re-runs the scenario LIVE — committed claims must re-derive.

Usage:
  python tools/chaos_serve.py [--replicas 3] [--requests 18]
      [--kill-replica 1] [--seed 0] [--smoke] [--json]
      [--evidence OUT.json]
"""

import argparse
import hashlib
import json
import logging
import os
import random
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

# p99 gate: generous (CPU-backend timing on a shared container is
# noisy) but BOUNDED — chaos must not turn tail latency into an outage
P99_RATIO_BOUND = 15.0
P99_FLOOR_S = 2.0


def _p99(samples):
    if not samples:
        return 0.0
    s = sorted(samples)
    return s[min(int(len(s) * 0.99), len(s) - 1)]


def make_builder(cfg, version="1"):
    def builder():
        from paddle_tpu.serving.decode import build_decoder_model

        extra = {k: cfg[k] for k in ("block_size", "num_blocks")
                 if k in cfg}
        return build_decoder_model(
            vocab_size=cfg["vocab_size"], hidden=cfg["hidden"],
            num_layers=cfg["num_layers"], slots=cfg["slots"],
            max_len=cfg["max_len"], name=cfg["model_name"],
            version=version, **extra,
        )
    return builder


def make_workload(cfg):
    """The deterministic open-loop request set: seeded prompts (with
    repeats, so prefix affinity has something to dedup) + fixed
    max_new."""
    rng = random.Random(cfg["seed"])
    prompts = []
    for i in range(cfg["requests"]):
        if i > 0 and rng.random() < 0.35:
            prompts.append(list(rng.choice(prompts)))  # repeat: prefix hit
        else:
            prompts.append([rng.randrange(cfg["vocab_size"])
                            for _ in range(rng.randrange(1, 5))])
    return prompts


def offline_references(cfg, prompts):
    """Single-replica offline reference per unique prompt — THE bytes
    every fleet-served generation must match, however many replicas or
    failovers were involved. Building this entry also warms the
    process compile cache, so every fleet replica below lowers without
    tracing."""
    from paddle_tpu.serving.decode import GenerationEngine

    engine = GenerationEngine(breaker_threshold=0, label="chaos-ref")
    entry = engine.register_model(make_builder(cfg))
    refs = {}
    for p in prompts:
        key = tuple(p)
        if key not in refs:
            refs[key] = entry.offline_decode(p, cfg["max_new"])
    return refs


def run_leg(cfg, prompts, kill=False):
    """One open-loop leg through a fresh 3-replica router. With
    ``kill``, the victim replica dies (via the ``replica.kill`` fault
    site) at its first heartbeat after it is observed holding live
    work, and autoscale must replace it with a zero-trace replica."""
    from paddle_tpu.resilience import faults
    from paddle_tpu.serving.fleet import FleetRouter, LocalReplica

    builder = make_builder(cfg)

    def factory(index):
        return LocalReplica.create(f"r{index}", index, builder,
                                   queue_depth=cfg["requests"] * 2 + 8)

    router = FleetRouter(
        replica_factory=factory, health_interval_s=0.02,
        min_replicas=cfg["replicas"], max_replicas=cfg["replicas"] + 1,
        autoscale=kill, breaker_threshold=3,
        label=f"chaos-{'kill' if kill else 'base'}-{cfg['seed']}",
    )
    for i in range(cfg["replicas"]):
        router.add_replica(factory(i))
    router.start()
    victim = f"r{cfg['kill_replica']}"
    armed = False
    responses = []
    submit_t = []
    try:
        for i, p in enumerate(prompts):
            responses.append(router.submit(p, max_new_tokens=cfg["max_new"]))
            submit_t.append(time.perf_counter())
            if kill and not armed:
                with router._lock:
                    holding = sum(
                        1 for rr in router._inflight.values()
                        if rr.replica == victim and rr.state == "inflight")
                # arm once the victim holds live work (mid-flight kill);
                # fall back to arming on the last submit so the kill
                # always fires even under a pathological affinity split
                if holding >= 2 or i == len(prompts) - 1:
                    faults.configure([{
                        "site": "replica.kill", "action": "raise",
                        "rank": cfg["kill_replica"], "id": "chaos-kill-r12",
                    }])
                    armed = True
            time.sleep(cfg["arrival_s"])
        outs = []
        lat = []
        for r, t0 in zip(responses, submit_t):
            res = r.result(timeout=240)
            outs.append([int(t) for t in res["tokens"]])
            lat.append(r.finish_time - t0)
        if kill:
            # the dead replica's autoscale replacement must arrive and
            # be serving-ready with zero traces
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                if router.metrics.count("scale_ups") >= 1:
                    break
                time.sleep(0.02)
        stats = router.stats()
        fired = {}
        inj = faults.get_injector()
        if inj is not None:
            fired = {k: v["fired"] for k, v in inj.rule_stats().items()}
        return {"outs": outs, "latencies": lat, "stats": stats,
                "rule_fired": fired,
                "scaleup_traces": router.last_scaleup_traces}
    finally:
        faults.reset()
        router.shutdown()


def run_scenario(cfg):
    """Both legs + the invariant checks; returns the full report. The
    deterministic half (config, invariants, token digest) is what the
    evidence file commits and the drift gate recomputes."""
    prompts = make_workload(cfg)
    refs = offline_references(cfg, prompts)

    base = run_leg(cfg, prompts, kill=False)
    chaos = run_leg(cfg, prompts, kill=True)

    failures = []

    def check(ok, msg):
        if not ok:
            failures.append(msg)
        return ok

    for leg_name, leg in (("baseline", base), ("chaos", chaos)):
        st = leg["stats"]
        check(st["accepted"] == cfg["requests"],
              f"{leg_name}: accepted {st['accepted']} != "
              f"{cfg['requests']}")
        check(st["completed"] == st["accepted"],
              f"{leg_name}: ZERO-LOSS VIOLATED — accepted "
              f"{st['accepted']} but completed {st['completed']} "
              f"(failed={st['failed']} deadline={st['deadline_missed']} "
              f"drained={st['drained_unserved']})")
        bad = [i for i, (p, o) in enumerate(zip(prompts, leg["outs"]))
               if o != refs[tuple(p)]]
        check(not bad,
              f"{leg_name}: BIT-IDENTITY VIOLATED on requests {bad[:5]}")

    cst = chaos["stats"]
    check(chaos["rule_fired"].get("chaos-kill-r12", 0) == 1,
          "the replica.kill fault never fired")
    check(cst["replica_deaths"] == 1,
          f"expected exactly 1 replica death, saw {cst['replica_deaths']}")
    check(cst["rerouted"] >= 1,
          f"kill landed with nothing to re-dispatch (rerouted="
          f"{cst['rerouted']}) — not a mid-flight kill")
    check(cst["scale_ups"] >= 1, "autoscale never replaced the victim")
    check(chaos["scaleup_traces"] == 0,
          f"scale-up replica paid {chaos['scaleup_traces']} traces "
          "(warm pool broken)")

    p99_base = _p99(base["latencies"])
    p99_chaos = _p99(chaos["latencies"])
    bound = max(P99_RATIO_BOUND * p99_base, P99_FLOOR_S)
    check(p99_chaos <= bound,
          f"p99 under chaos {p99_chaos:.3f}s exceeds bound {bound:.3f}s "
          f"(baseline {p99_base:.3f}s)")

    digest = hashlib.sha256(json.dumps(
        [[i, out] for i, out in enumerate(chaos["outs"])]
    ).encode()).hexdigest()

    report = {
        "scenario": {k: cfg[k] for k in sorted(cfg)},
        "invariants": {
            "accepted": cst["accepted"],
            "completed": cst["completed"],
            "lost": cst["accepted"] - cst["completed"],
            "bit_identical": not any("BIT-IDENTITY" in f
                                     for f in failures),
            "kill_fired": chaos["rule_fired"].get("chaos-kill-r12",
                                                  0) == 1,
            "replica_deaths": cst["replica_deaths"],
            "scaleup_traces": chaos["scaleup_traces"],
            "unique_prompts": len(refs),
            "tokens_digest": digest,
        },
        "measured": {
            "rerouted": cst["rerouted"],
            "stolen_queued": cst["stolen_queued"],
            "breaker_probes": cst["breaker_probes"],
            "p99_base_ms": round(p99_base * 1e3, 1),
            "p99_chaos_ms": round(p99_chaos * 1e3, 1),
            "p99_bound_ms": round(bound * 1e3, 1),
            "replica_states": {rid: r["state"] for rid, r in
                               cst["replicas"].items()},
        },
        "failures": failures,
    }
    return report


def run_overload_scenario(cfg):
    """r18 overload leg: kill a replica WHILE it holds parked sessions.

    Two replicas with deliberately undersized block pools (12 rows, 2
    slots) serve an open-loop burst that oversubscribes the arenas, so
    the engines continuously park/resume sessions through the host KV
    tier. The ``replica.kill`` fault is armed the moment a replica is
    OBSERVED holding a parked session — the death lands while that
    session's KV lives only in the (now dead) replica's host tier. The
    router must re-dispatch everything the victim held — parked
    included — with ZERO loss and BIT-IDENTICAL bytes (a re-dispatched
    park restarts from the prompt on the new replica; decode determinism
    makes the restart invisible). The stream runs on the HIGH lane so
    the brownout ladder degrades but never sheds: this leg measures
    preemption + failover, not shedding."""
    from paddle_tpu.resilience import faults
    from paddle_tpu.serving.fleet import FleetRouter, LocalReplica
    from paddle_tpu.serving.request import Priority

    ocfg = dict(cfg, model_name="chaos_ov", slots=2, max_len=16,
                block_size=2, num_blocks=6, replicas=2,
                requests=max(8, cfg["requests"] // 2))
    rng = random.Random((ocfg["seed"], "overload"))
    prompts = [[rng.randrange(ocfg["vocab_size"]) for _ in range(4)]
               for _ in range(ocfg["requests"])]
    refs = offline_references(ocfg, prompts)
    builder = make_builder(ocfg)

    def factory(index):
        return LocalReplica.create(f"r{index}", index, builder,
                                   queue_depth=ocfg["requests"] * 2 + 8)

    router = FleetRouter(
        replica_factory=factory, health_interval_s=0.02,
        min_replicas=ocfg["replicas"], max_replicas=ocfg["replicas"] + 1,
        autoscale=True, breaker_threshold=3,
        label=f"chaos-ov-{ocfg['seed']}",
    )
    for i in range(ocfg["replicas"]):
        router.add_replica(factory(i))
    router.start()
    responses = []
    armed = False
    victim_rank = None
    parked_at_kill = 0
    try:
        for p in prompts:
            responses.append(router.submit(
                p, max_new_tokens=ocfg["max_new"],
                priority=Priority.HIGH))
        # watch the replicas until one holds a parked session, then arm
        # the kill on ITS rank; fall back to rank 0 if every park
        # resolved before we caught one mid-flight
        deadline = time.monotonic() + 30
        while not armed and time.monotonic() < deadline:
            if all(r.done() for r in responses):
                break
            for i in range(ocfg["replicas"]):
                rep = router._replicas.get(f"r{i}")
                if rep is None or getattr(rep, "engine", None) is None:
                    continue
                try:
                    st = rep.engine.entry(
                        ocfg["model_name"], "1").stats()
                except KeyError:
                    continue
                if st["parked_sessions"] >= 1:
                    victim_rank = i
                    parked_at_kill = st["parked_sessions"]
                    break
            if victim_rank is not None:
                faults.configure([{
                    "site": "replica.kill", "action": "raise",
                    "rank": victim_rank, "id": "chaos-kill-r18",
                }])
                armed = True
            else:
                time.sleep(0.001)
        if not armed and not all(r.done() for r in responses):
            victim_rank = 0
            faults.configure([{
                "site": "replica.kill", "action": "raise",
                "rank": 0, "id": "chaos-kill-r18",
            }])
            armed = True
        outs = [[int(t) for t in r.result(timeout=240)["tokens"]]
                for r in responses]
        fired = {}
        inj = faults.get_injector()
        if inj is not None:
            fired = {k: v["fired"] for k, v in inj.rule_stats().items()}
        stats = router.stats()
    finally:
        faults.reset()
        router.shutdown()

    failures = []

    def check(ok, msg):
        if not ok:
            failures.append(msg)

    check(stats["accepted"] == ocfg["requests"],
          f"overload: accepted {stats['accepted']} != "
          f"{ocfg['requests']}")
    check(stats["completed"] == stats["accepted"],
          f"overload: ZERO-LOSS VIOLATED — accepted {stats['accepted']} "
          f"completed {stats['completed']} (failed={stats['failed']})")
    bad = [i for i, (p, o) in enumerate(zip(prompts, outs))
           if o != refs[tuple(p)]]
    check(not bad,
          f"overload: BIT-IDENTITY VIOLATED on requests {bad[:5]}")
    killed = fired.get("chaos-kill-r18", 0)
    check(killed == 1 if armed else killed == 0,
          f"overload: replica.kill fired {killed} times (armed={armed})")
    return {
        "config": {k: ocfg[k] for k in sorted(ocfg)},
        "invariants": {
            "accepted": stats["accepted"],
            "completed": stats["completed"],
            "lost": stats["accepted"] - stats["completed"],
            "bit_identical": not bad,
            "kill_fired": killed == 1,
            "parked_at_kill": parked_at_kill,
            "victim": victim_rank,
        },
        "failures": failures,
    }


def default_cfg(args):
    return {
        "replicas": args.replicas,
        "requests": args.requests,
        "max_new": args.max_new,
        "kill_replica": args.kill_replica,
        "seed": args.seed,
        "arrival_s": args.arrival_s,
        "vocab_size": 24,
        "hidden": 8,
        "num_layers": 1,
        "slots": 2,
        "max_len": 16,
        "model_name": "chaos",
    }


def _write_evidence(path, report):
    payload = {
        "issue": 12,
        "generated_by": ("python tools/chaos_serve.py --evidence "
                         "FLEET_EVIDENCE_r12.json"),
        "drift_gates": [
            "tests/test_fleet_serving.py::test_fleet_evidence_r12_committed",
            "tools/chaos_serve.py --smoke (tier-1 wiring: "
            "tests/test_fleet_serving.py)",
        ],
        "scenario": report["scenario"],
        "invariants": report["invariants"],
        # informational: timing/interleaving-dependent, NOT drift-gated
        "measured": report["measured"],
    }
    with open(path, "w") as f:
        json.dump(payload, f, indent=1, sort_keys=True)
        f.write("\n")
    print(f"wrote {path}: lost={payload['invariants']['lost']} "
          f"bit_identical={payload['invariants']['bit_identical']} "
          f"scaleup_traces={payload['invariants']['scaleup_traces']} "
          f"rerouted={payload['measured']['rerouted']}")


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--replicas", type=int, default=3)
    ap.add_argument("--requests", type=int, default=18)
    ap.add_argument("--max-new", type=int, default=6)
    ap.add_argument("--kill-replica", type=int, default=1)
    # default seed chosen so the workload exercises prompt REPEATS
    # (13 unique of 18: prefix-affinity + prefix-cache dedup both fire)
    ap.add_argument("--seed", type=int, default=2)
    ap.add_argument("--arrival-s", type=float, default=0.002,
                    help="open-loop inter-arrival gap")
    ap.add_argument("--smoke", action="store_true",
                    help="seconds-scale run + invariant asserts (CI)")
    ap.add_argument("--overload", action="store_true",
                    help="r18 leg only: kill a replica while it holds "
                         "parked sessions (smoke runs this too)")
    ap.add_argument("--evidence", metavar="OUT.json",
                    help="write the fleet evidence file")
    ap.add_argument("--json", action="store_true", dest="as_json")
    args = ap.parse_args(argv)

    logging.getLogger("paddle_tpu.resilience.faults").setLevel(
        logging.ERROR)
    cfg = default_cfg(args)
    t0 = time.perf_counter()
    if args.overload and not args.smoke:
        ov = run_overload_scenario(cfg)
        wall = time.perf_counter() - t0
        print(json.dumps(ov, indent=1))
        if ov["failures"]:
            for f in ov["failures"]:
                print(f"CHAOS FAIL: {f}", file=sys.stderr)
            return 1
        inv = ov["invariants"]
        print(f"CHAOS_OVERLOAD_OK requests={inv['accepted']} "
              f"lost={inv['lost']} parked_at_kill={inv['parked_at_kill']} "
              f"victim=r{inv['victim']} wall={wall:.1f}s")
        return 0
    report = run_scenario(cfg)
    if args.smoke or args.overload:
        ov = run_overload_scenario(cfg)
        report["overload"] = {"config": ov["config"],
                              "invariants": ov["invariants"]}
        report["failures"] = report["failures"] + ov["failures"]
    wall = time.perf_counter() - t0
    if args.evidence:
        _write_evidence(args.evidence, report)
    if args.as_json:
        print(json.dumps({"pass": not report["failures"], **report,
                          "wall_s": round(wall, 1)}))
    else:
        print(json.dumps(report, indent=1))
    if report["failures"]:
        for f in report["failures"]:
            print(f"CHAOS FAIL: {f}", file=sys.stderr)
        return 1
    inv = report["invariants"]
    print(f"CHAOS_SERVE_OK requests={inv['accepted']} lost={inv['lost']} "
          f"rerouted={report['measured']['rerouted']} "
          f"scaleup_traces={inv['scaleup_traces']} "
          f"p99 {report['measured']['p99_base_ms']}ms -> "
          f"{report['measured']['p99_chaos_ms']}ms wall={wall:.1f}s")
    return 0


if __name__ == "__main__":
    sys.exit(main())
