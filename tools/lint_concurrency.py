#!/usr/bin/env python
"""Concurrency lint CLI: static lock-order + race analysis over sources.

CI contract (shared with tools/lint_program.py): exit 0 = clean,
1 = lint findings, 2 = internal error / bad invocation; ``--json`` emits
one machine-readable report line.

  python tools/lint_concurrency.py                  # lint paddle_tpu/
  python tools/lint_concurrency.py path/a.py dir/   # lint specific paths
  python tools/lint_concurrency.py --json
  python tools/lint_concurrency.py --smoke          # the fast-tier gate

``--smoke`` is the r11 CI gate:
  1. the repo-wide static lint is CLEAN — every remaining finding either
     fixed or carrying an attributed ``# lockdep: ok(reason)``;
  2. both synthetic positive controls FIRE with correct file:line and
     held-chain attribution (an injected ABBA pair and an unguarded-dict
     mutation; a blocking-under-lock control rides along) — the gate is
     proven live, not vacuously green;
  3. the runtime lockdep witness raises on a live ABBA inversion and on
     a declared-hierarchy violation (observability/lockdep.py);
  4. the static half of CONCURRENCY_EVIDENCE_r11.json matches a fresh
     recompute (drift = the analyzer or the sources changed without
     regenerating evidence — run
     ``python tools/stress_concurrency.py --evidence
     CONCURRENCY_EVIDENCE_r11.json``). The runtime (lockdep) half is
     drift-gated by tests/test_concurrency.py.
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
EXIT_CLEAN, EXIT_FINDINGS, EXIT_INTERNAL = 0, 1, 2

# ---------------------------------------------------------------------------
# synthetic positive controls (imported by tests/test_concurrency.py too):
# if the analyzer ever stops firing on these, the smoke gate fails — a
# silently-dead linter must not read as a clean repo
# ---------------------------------------------------------------------------

ABBA_CONTROL = '''\
import threading


class Control:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()

    def forward(self):
        with self._a:
            with self._b:
                pass

    def backward(self):
        with self._b:
            with self._a:
                pass
'''
# forward's inner `with self._b:` is control line 11; backward's inner
# `with self._a:` is control line 16 (asserted by the smoke)
ABBA_LINES = (11, 16)

UNGUARDED_CONTROL = '''\
import threading


class Worker:
    def __init__(self):
        self._lock = threading.Lock()
        self.counts = {}
        self._thread = threading.Thread(target=self._loop, daemon=True)

    def _loop(self):
        self.counts["ticks"] = self.counts.get("ticks", 0) + 1

    def snapshot(self):
        with self._lock:
            return dict(self.counts)
'''
UNGUARDED_LINE = 11

BLOCKING_CONTROL = '''\
import threading


class Blocker:
    def __init__(self):
        self._lock = threading.Lock()
        self._thread = threading.Thread(target=self._loop)

    def _loop(self):
        pass

    def stop(self):
        with self._lock:
            self._thread.join()
'''
BLOCKING_LINE = 14

DEFAULT_PATHS = (os.path.join(REPO, "paddle_tpu"),)


def _scan(paths):
    from paddle_tpu.analysis.concurrency import scan_paths

    return scan_paths(list(paths))


def _print_report(rep, as_json, out=sys.stdout):
    if as_json:
        payload = rep.to_json()
        payload["pass"] = not rep.findings
        out.write(json.dumps(payload) + "\n")
        return
    for f in rep.findings:
        out.write(f"{f}\n")
    for f in rep.suppressed:
        out.write(f"{f}\n")
    for e in rep.edges:
        out.write(f"edge: {e.describe()}\n")
    out.write(
        f"[concurrency] {rep.files} files, {len(rep.locks)} locks, "
        f"{len(rep.edges)} hold-edges, {len(rep.cycles)} cycles, "
        f"{len(rep.findings)} findings "
        f"({len(rep.suppressed)} suppressed)\n"
    )


# ---------------------------------------------------------------------------
# smoke
# ---------------------------------------------------------------------------


def static_section(rep):
    """The static half of CONCURRENCY_EVIDENCE_r11.json, derived from a
    Report — ONE definition shared by the evidence generator
    (tools/stress_concurrency.py) and the drift checks here/in tests.
    Suppression entries carry (file, reason) — not line numbers, which
    would drift on every unrelated edit."""
    return {
        "files": rep.files,
        "lock_ids": sorted(l.id for l in rep.locks),
        "unsuppressed_findings": len(rep.findings),
        "cycles": rep.cycles,
        "hold_edges": sorted({(e.a, e.b) for e in rep.edges}),
        "suppressions": sorted(
            {(f.file, f.suppress_reason) for f in rep.suppressed}
        ),
    }


def _norm(section):
    """Committed JSON turns tuples into lists; normalize both sides."""
    return json.loads(json.dumps(section))


def _smoke(as_json):
    from paddle_tpu.analysis.concurrency import scan_sources

    failures = []

    def check(ok, msg):
        if not ok:
            failures.append(msg)
            print(f"SMOKE FAIL: {msg}", file=sys.stderr)

    # 1. repo-wide lint must be clean (suppressions allowed + reported)
    rep = _scan(DEFAULT_PATHS)
    for f in rep.findings:
        print(f"SMOKE FAIL: unsuppressed finding: {f}", file=sys.stderr)
    check(not rep.findings,
          f"{len(rep.findings)} unsuppressed concurrency findings in "
          f"paddle_tpu/ (fix or add '# lockdep: ok(reason)')")
    check(not rep.cycles, f"static lock-order cycles: {rep.cycles}")

    # 2. positive controls fire with correct attribution
    abba = scan_sources({"<control-abba>": ABBA_CONTROL})
    cyc = [f for f in abba.findings if f.kind == "lock-order-cycle"]
    check(len(cyc) == 1, "ABBA control did not produce a cycle finding")
    if cyc:
        check(cyc[0].file == "<control-abba>"
              and cyc[0].line in ABBA_LINES,
              f"ABBA control attribution wrong: {cyc[0].file}:{cyc[0].line}")
        check("._a" in cyc[0].message and "._b" in cyc[0].message
              and "holding" in cyc[0].message,
              "ABBA control message lacks held-chain attribution")
        check(str(ABBA_LINES[0]) in cyc[0].message
              and str(ABBA_LINES[1]) in cyc[0].message,
              "ABBA control message lacks both edge lines")

    ung = scan_sources({"<control-unguarded>": UNGUARDED_CONTROL})
    mut = [f for f in ung.findings
           if f.kind == "unguarded-shared-mutation"]
    check(len(mut) == 1 and mut[0].line == UNGUARDED_LINE,
          f"unguarded-dict control did not fire at line {UNGUARDED_LINE}: "
          f"{[str(f) for f in ung.findings]}")

    blk = scan_sources({"<control-blocking>": BLOCKING_CONTROL})
    bf = [f for f in blk.findings if f.kind == "blocking-under-lock"]
    check(len(bf) == 1 and bf[0].line == BLOCKING_LINE
          and bf[0].held == ("<control-blocking>.Blocker._lock",),
          f"blocking control did not fire with held chain: "
          f"{[str(f) for f in blk.findings]}")

    # 3. the runtime witness is live: ABBA + declared-order violations
    from paddle_tpu.observability import lockdep

    was = lockdep.enabled()
    try:
        lockdep.enable()
        lockdep.reset()
        a = lockdep.named_lock("lintctl.a")
        b = lockdep.named_lock("lintctl.b")
        with a:
            with b:
                pass
        raised = False
        try:
            with b:
                with a:
                    pass
        except lockdep.LockOrderError as e:
            raised = "lintctl.a" in str(e) and "lintctl.b" in str(e)
        check(raised, "runtime witness did not raise on a live ABBA")
        lockdep.reset()
        # the repo's own declared hierarchy enforces (decode engine
        # declares serving.queue before decode.tenant at import)
        import paddle_tpu.serving.decode.engine  # noqa: F401 - declares

        q = lockdep.named_lock("serving.queue", rlock=True)
        t = lockdep.named_lock("decode.tenant")
        raised = False
        try:
            with t:
                with q:
                    pass
        except lockdep.LockOrderError as e:
            raised = "declared lock order" in str(e)
        check(raised,
              "runtime witness did not enforce the declared "
              "serving.queue -> decode.tenant hierarchy")
    finally:
        lockdep.reset()
        lockdep.enable(was)

    # 4. static evidence drift gate
    path = os.path.join(REPO, "CONCURRENCY_EVIDENCE_r11.json")
    if not os.path.exists(path):
        check(False,
              "CONCURRENCY_EVIDENCE_r11.json missing (run "
              "tools/stress_concurrency.py --evidence "
              "CONCURRENCY_EVIDENCE_r11.json)")
    else:
        with open(path) as f:
            committed = json.load(f)
        fresh = _norm(static_section(rep))
        want = committed.get("static", {})
        for key in sorted(set(fresh) | set(want)):
            check(want.get(key) == fresh.get(key),
                  f"static evidence drift in '{key}': committed "
                  f"{want.get(key)!r} != fresh {fresh.get(key)!r}")

    if not failures:
        print(f"smoke: concurrency lint clean over {rep.files} files "
              f"({len(rep.locks)} locks, {len(rep.suppressed)} attributed "
              f"suppressions), all 3 static controls + 2 runtime witness "
              f"controls fired, static evidence matches")
    if as_json:
        print(json.dumps({"pass": not failures, "failures": failures,
                          "files": rep.files, "locks": len(rep.locks),
                          "suppressed": len(rep.suppressed)}))
    return EXIT_FINDINGS if failures else EXIT_CLEAN


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="static concurrency lint (lock order, blocking under "
        "lock, unguarded shared mutation)")
    ap.add_argument("paths", nargs="*",
                    help="files/directories (default: paddle_tpu/)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="one JSON report line")
    ap.add_argument("--smoke", action="store_true",
                    help="fast-tier CI gate: repo clean + positive "
                    "controls fire + static evidence matches")
    try:
        args = ap.parse_args(argv)
        if args.smoke:
            return _smoke(args.as_json)
        rep = _scan(args.paths or DEFAULT_PATHS)
        _print_report(rep, args.as_json)
        return EXIT_FINDINGS if rep.findings else EXIT_CLEAN
    except SystemExit as e:
        # argparse errors exit 2 already; preserve the 0/1/2 contract
        raise SystemExit(EXIT_INTERNAL if e.code not in (0, 1, 2)
                         else e.code)
    except Exception:
        import traceback

        traceback.print_exc()
        return EXIT_INTERNAL


if __name__ == "__main__":
    sys.exit(main())
