#!/usr/bin/env python
"""COST_EVIDENCE_r16 generator: static roofline predictions vs XLA.

Round 16's claim is that step time, MFU, and collective cost are
*pre-compile* quantities: analysis/cost.py walks the op plan — no XLA in
the loop — and assigns every op FLOPs, HBM bytes, and wire bytes, folded
through a mesh-aware machine model. This tool makes that falsifiable the
r09 way. For each evidence arm it records

  static:  the analyzer's prediction — total FLOPs, predicted step
           seconds, MFU, roofline bound-class counts, per-axis
           collective budget, op coverage (unknown_ops MUST be empty)
  live:    the same program actually lowered and compiled, with
           ``jax.jit(...).lower().compile().cost_analysis()`` FLOPs
           (per-device partitioned numbers on the mesh arm)
  match:   the static/XLA FLOP ratio against a committed per-arm
           tolerance

plus two static-only control arms: ``dcn_linter_control`` (a mesh with a
declared 'dcn' axis where the hierarchical-collective linter MUST fire)
and ``pipeline_bubble`` (a pipeline_stack program whose GPipe bubble
fraction is predicted). tests/test_cost_analysis.py::
test_cost_evidence_r16_committed re-derives the static half
byte-for-byte and ``--smoke`` does the same in tier-1, so the committed
numbers cannot drift silently.

Usage: python tools/cost_report.py [--out COST_EVIDENCE_r16.json]
       python tools/cost_report.py --smoke   # static half vs committed
       (full run ~2 min on the CPU rig; --smoke is seconds)
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

MACHINE = "tpu-v4-8"
EXAMPLE_BATCH = 16
BERT_GEOMETRY = {"batch": 8, "seq_len": 24, "max_pred": 20}
# committed static/XLA FLOP-ratio bounds (symmetric max/min ratio).
# Single-device arms calibrate ~1.02-1.09 (the slack is XLA folding
# transcendental-heavy ops); the SPMD arm ~1.35 (GSPMD rewrites pad the
# per-device graph with halo/select flops the static model ignores).
TOLERANCES = {"fit_a_line": 1.25, "recognize_digits": 1.25,
              "tp_bert": 2.0}
EVIDENCE = "COST_EVIDENCE_r16.json"


def _load_example(name):
    """examples/<name>.py train program with deferred rewrites applied —
    identical to the static_report.py loader."""
    import importlib.util

    from paddle_tpu.passes import (
        apply_deferred_sharded_embedding_rewrite,
        apply_deferred_sparse_rewrite,
    )

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        f"cr_example_{name}", os.path.join(repo, "examples", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    main, startup, feed_names, fetches = mod.build_programs()[:4]
    apply_deferred_sparse_rewrite(main)
    apply_deferred_sharded_embedding_rewrite(main)
    fetch_names = [f if isinstance(f, str) else f.name for f in fetches]
    return main, startup, list(feed_names), fetch_names


def _synthetic_feed(main, feed_names, batch):
    """name -> ndarray with the symbolic batch dim bound, dtypes from the
    feed vars (int feeds get zeros — always a valid class/token id)."""
    import numpy as np

    rng = np.random.RandomState(0)
    block = main.global_block()
    feed = {}
    for fname in feed_names:
        v = block._find_var_recursive(fname)
        shape = tuple(batch if d is None or d < 0 else int(d)
                      for d in v.shape)
        dt = str(getattr(v, "dtype", "float32") or "float32")
        if "int" in dt:
            feed[fname] = np.zeros(shape, dtype=dt)
        else:
            feed[fname] = rng.uniform(0.0, 1.0, shape).astype(dt)
    return feed


def _static_summary(rep):
    return {
        "machine": rep.cost_model.machine.name,
        "ops": len(rep.ops),
        "unknown_ops": sorted(rep.unknown_ops),
        "total_flops": rep.total_flops,
        "total_transcendentals": rep.total_transcendentals,
        "total_hbm_bytes": rep.total_hbm_bytes,
        "step_seconds": round(rep.step_seconds, 12),
        "mfu": round(rep.mfu, 6),
        "bound_counts": rep.bound_counts(),
        "collective_seconds": round(rep.collective_seconds, 12),
        "per_axis": rep.per_axis(),
    }


def _bert_arm_inputs():
    import numpy as np

    from paddle_tpu.models import bert

    cfg = bert.BertConfig.tiny()
    cfg.hidden_dropout_prob = 0.0
    cfg.attention_probs_dropout_prob = 0.0
    main, startup, feeds, fetches = bert.build_bert_pretrain(
        cfg, seq_len=BERT_GEOMETRY["seq_len"], lr=1e-3,
        max_predictions_per_seq=BERT_GEOMETRY["max_pred"],
    )
    data = bert.synthetic_batch(
        np.random.RandomState(0), BERT_GEOMETRY["batch"],
        BERT_GEOMETRY["seq_len"], cfg,
        max_predictions_per_seq=BERT_GEOMETRY["max_pred"],
    )
    return main, startup, data, fetches


def static_sections():
    """arm -> static prediction (the half --smoke and the evidence test
    recompute byte-for-byte; NO lowering happens here)."""
    from paddle_tpu.analysis.cost import (
        analyze_cost,
        hierarchical_collective_diagnostics,
        pipeline_bubble_report,
    )
    from paddle_tpu.parallel.env import make_mesh
    from paddle_tpu.parallel.spec_layout import SpecLayout

    out = {}

    for name in ("fit_a_line", "recognize_digits"):
        main, _startup, feed_names, fetch_names = _load_example(name)
        feed = _synthetic_feed(main, feed_names, EXAMPLE_BATCH)
        rep = analyze_cost(
            main, machine=MACHINE,
            feed_shapes={k: v.shape for k, v in feed.items()},
            fetch_names=fetch_names,
        )
        out[name] = _static_summary(rep)

    main, _startup, data, fetches = _bert_arm_inputs()
    mesh = make_mesh((2, 4), ("data", "model"))
    rep = analyze_cost(
        main, machine=MACHINE, mesh=mesh, spec_layout=SpecLayout(),
        feed_shapes={k: v.shape for k, v in data.items()},
        fetch_names=[fetches[0].name],
    )
    sec = _static_summary(rep)
    sec["mesh"] = {"shape": [2, 4], "axes": ["data", "model"]}
    out["tp_bert"] = sec

    # positive control: a 'dcn'-tagged outer data axis with the batch
    # split over (dcn, data) — every grad-sync all-reduce then spans DCN
    # at full payload and the hierarchical linter MUST fire.
    from jax.sharding import PartitionSpec as P

    from paddle_tpu.models import mnist

    cmain, _cstartup, cfeeds, cfetches = mnist.build_mnist_train()
    cfeed_names = [f if isinstance(f, str) else f.name for f in cfeeds]
    cfetch_names = [f if isinstance(f, str) else f.name for f in cfetches]
    cmesh = make_mesh((2, 4), ("dcn", "data"))
    cfeed = _synthetic_feed(cmain, cfeed_names, EXAMPLE_BATCH)
    crep = analyze_cost(
        cmain, machine=MACHINE, mesh=cmesh,
        axis_tags={"dcn": "dcn", "data": "ici"},
        input_specs={n: P(("dcn", "data")) for n in cfeed_names},
        feed_shapes={k: v.shape for k, v in cfeed.items()},
        fetch_names=cfetch_names,
    )
    diags = hierarchical_collective_diagnostics(crep)
    out["dcn_linter_control"] = {
        "mesh": {"shape": [2, 4], "axes": ["dcn", "data"]},
        "axis_tags": {"dcn": "dcn", "data": "ici"},
        "collectives": len(crep.collectives),
        "dcn_all_reduces": sum(
            1 for c in crep.collectives if c["kind"] == "all-reduce"
            and "dcn" in c["tags"]),
        "linter_fired": len(diags),
        "codes": sorted({d.code for d in diags}),
        "flagged_vars": sorted(d.var for d in diags),
        "dcn_bytes_saved": sum(
            int(c["bytes"] * (1 - 1.0 / 4)) for c in crep.collectives
            if c["kind"] == "all-reduce" and "dcn" in c["tags"]),
    }

    # bubble arm: ONE pipeline_stack op, 4 layers as 4 stages over 4
    # microbatches -> GPipe bubble (s-1)/(m+s-1) = 3/7.
    from paddle_tpu.models import gpt_ir

    gcfg = gpt_ir.GPTIRConfig()
    gmain, _gs, _gf, gloss, _stack = gpt_ir.build_gpt_ir(
        gcfg, seq_len=16, num_microbatches=4)
    gshapes = {"tokens": (8, 16), "labels": (8, 16)}
    grep = analyze_cost(
        gmain, machine=MACHINE, feed_shapes=gshapes,
        fetch_names=[gloss.name], num_stages=4,
    )
    bub = pipeline_bubble_report(gmain, feed_shapes=gshapes, num_stages=4)
    out["pipeline_bubble"] = {
        "unknown_ops": sorted(grep.unknown_ops),
        "total_flops": grep.total_flops,
        "pipeline": bub,
    }
    return out


def live_sections():
    """arm -> XLA ground truth: lower + compile each runnable arm and
    read cost_analysis() FLOPs (per-device on the mesh arm)."""
    import paddle_tpu as fluid
    from paddle_tpu.parallel.env import make_mesh
    from paddle_tpu.parallel.spec_layout import SpecLayout
    from paddle_tpu.utils import hlo

    def _xla_flops(lowered):
        ca = lowered.compile().cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0]
        return int(ca.get("flops", 0)), int(ca.get("transcendentals", 0))

    out = {}
    for name in ("fit_a_line", "recognize_digits"):
        main, startup, feed_names, fetch_names = _load_example(name)
        feed = _synthetic_feed(main, feed_names, EXAMPLE_BATCH)
        scope = fluid.Scope()
        exe = fluid.Executor(fluid.CPUPlace())
        with fluid.scope_guard(scope):
            exe.run(startup)
            lowered = hlo.lower_program_step(
                main, feed, fetch_names, scope=scope)
        flops, trans = _xla_flops(lowered)
        out[name] = {"xla_flops": flops, "xla_transcendentals": trans}

    main, startup, data, fetches = _bert_arm_inputs()
    mesh = make_mesh((2, 4), ("data", "model"))
    prog = fluid.CompiledProgram(main).with_parallel(
        mesh=mesh, loss_name=fetches[0].name, spec_layout=SpecLayout())
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)
        lowered, _ = hlo.lower_parallel_step(
            exe, prog, data, [fetches[0]], scope)
    flops, trans = _xla_flops(lowered)
    out["tp_bert"] = {"xla_flops": flops, "xla_transcendentals": trans,
                      "note": "per-device partitioned flops (SPMD)"}
    return out


def match_sections(static, live):
    out = {}
    for tag, tol in TOLERANCES.items():
        pred = static[tag]["total_flops"]
        got = live[tag]["xla_flops"]
        ratio = max(pred, got) / max(min(pred, got), 1)
        out[tag] = {
            "static_flops": pred,
            "xla_flops": got,
            "flops_ratio": round(ratio, 4),
            "tolerance": tol,
            "verdict": "pass" if ratio <= tol else "fail",
        }
    return out


def build_report(with_live=True):
    static = static_sections()
    report = {
        "machine": MACHINE,
        "example_batch": EXAMPLE_BATCH,
        "bert_geometry": BERT_GEOMETRY,
        "tolerances": TOLERANCES,
        "arms": {tag: {"static": sec} for tag, sec in static.items()},
    }
    if with_live:
        live = live_sections()
        match = match_sections(static, live)
        for tag in live:
            report["arms"][tag]["live"] = live[tag]
            report["arms"][tag]["match"] = match[tag]
    return report


def smoke():
    """Recompute the static half and compare byte-for-byte against the
    committed evidence; verify control invariants. Exit 1 on drift."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = os.path.join(repo, EVIDENCE)
    with open(path) as f:
        committed = json.load(f)
    fresh = static_sections()
    failures = []
    for tag, sec in fresh.items():
        old = committed["arms"].get(tag, {}).get("static")
        if json.dumps(old, sort_keys=True) != json.dumps(
                sec, sort_keys=True):
            failures.append(f"static drift on arm '{tag}'")
    if not committed["arms"]["dcn_linter_control"]["static"][
            "linter_fired"]:
        failures.append("dcn linter control did not fire")
    for tag, m in ((t, committed["arms"][t].get("match"))
                   for t in TOLERANCES):
        if not m or m["verdict"] != "pass":
            failures.append(f"match verdict not 'pass' on arm '{tag}'")
    bub = committed["arms"]["pipeline_bubble"]["static"]["pipeline"]
    if not bub or not bub[0]["bubble_fraction"] > 0:
        failures.append("no positive pipeline bubble prediction")
    for msg in failures:
        print("FAIL:", msg)
    if not failures:
        print(f"smoke OK: {len(fresh)} arms, static half matches "
              f"{EVIDENCE}")
    return 1 if failures else 0


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=None,
                    help="also write the JSON report to this path")
    ap.add_argument("--static-only", action="store_true",
                    help="skip the XLA compile half (seconds)")
    ap.add_argument("--smoke", action="store_true",
                    help="recompute the static half and diff it against "
                    "the committed evidence file; exit 1 on drift")
    args = ap.parse_args()
    if args.smoke:
        sys.exit(smoke())
    report = build_report(with_live=not args.static_only)
    text = json.dumps(report, indent=1)
    print(text)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text + "\n")


if __name__ == "__main__":
    main()
