#!/usr/bin/env python
"""Input-pipeline throughput benchmark: samples/s vs num_workers.

Measures the dataio-backed DataLoader on a CPU-bound preprocessing
workload (per-sample numpy matmul chain — BLAS releases the GIL, which
is exactly the decode/augment profile the thread pool is built for)
against the single-thread baseline (num_workers=0: same code path,
transform inline). Also verifies the determinism contract while it's at
it: every worker count must produce the identical batch stream.

`--smoke` is the tier-1 CI hook (wired by tests/test_dataio.py):
a seconds-scale run asserting the acceptance invariants — >= 2x
samples/s at num_workers=4 over the single-thread DataLoader, identical
batch streams across worker counts, and `dataio::` spans + queue-depth
gauges visible in a captured Chrome trace / the metrics registry.

Usage:
  python tools/bench_input.py [--samples 8192] [--batch-size 32]
      [--workers 0,1,2,4,8] [--work 64] [--smoke]
      [--trace-out /tmp/input.trace.json]
"""

import argparse
import hashlib
import json
import os
import sys
import tempfile
import time

# keep BLAS single-threaded so worker scaling is measured, not OpenMP's
os.environ.setdefault("OMP_NUM_THREADS", "1")
os.environ.setdefault("OPENBLAS_NUM_THREADS", "1")
os.environ.setdefault("MKL_NUM_THREADS", "1")
os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def make_reader(n_samples):
    def reader():
        for i in range(n_samples):
            yield (i,)

    return reader


def make_preprocess(work):
    """CPU-bound per-sample decode/augment stand-in: `work`x`work`
    float32 matmuls derived deterministically from the sample id. The
    cost must sit in GIL-RELEASING C (BLAS) — like real decode/resize —
    for a thread pool to scale it; pure-Python or tiny-array work is
    GIL-bound and parallelizes with processes, not threads (the
    README determinism-contract section documents this boundary)."""
    base = np.random.RandomState(0).rand(work, work).astype(np.float32)
    base /= np.linalg.norm(base, axis=1, keepdims=True)

    def preprocess(sample):
        (i,) = sample
        a = base + np.float32((int(i) % 97) * 1e-4)
        a = a @ base
        a = a @ base
        x = (a[0, :4] / (np.abs(a).max() + 1.0)).astype(np.float32)
        y = np.array([float(x.sum())], dtype=np.float32)
        return (x, y)

    return preprocess


def run_loader(n_samples, batch_size, num_workers, work, digest=False):
    """Consume one full pass; returns (samples_per_s, n_consumed, digest).
    digest=True hashes the batch stream (order-sensitive) so worker
    counts can be compared for bit-identical output."""
    import paddle_tpu as fluid
    from paddle_tpu.core.ir import Program, program_guard

    main, startup = Program(), Program()
    with program_guard(main, startup):
        x = fluid.data("x", shape=[-1, 4])
        y = fluid.data("y", shape=[-1, 1])
    loader = fluid.DataLoader.from_generator(
        feed_list=[x, y], capacity=8, num_workers=num_workers
    )
    loader.set_sample_generator(
        make_reader(n_samples), batch_size, drop_last=False,
        sample_transform=make_preprocess(work),
    )
    h = hashlib.sha256() if digest else None
    t0 = time.perf_counter()
    count = 0
    for feed in loader:
        count += int(feed["x"].shape[0])
        if h is not None:
            h.update(np.asarray(feed["x"]).tobytes())
            h.update(np.asarray(feed["y"]).tobytes())
    dt = time.perf_counter() - t0
    return count / dt, count, (h.hexdigest() if h else None)


def capture_trace(out_path, n_samples, batch_size, work):
    """Short traced pass: returns the span-name aggregate from the
    exported Chrome trace (PROFILE.md's input-pipeline timeline)."""
    from paddle_tpu import observability as obs

    obs.enable_tracing()
    try:
        run_loader(n_samples, batch_size, num_workers=4, work=work)
    finally:
        obs.disable_tracing()
    n_events = obs.export_chrome_trace(out_path)
    with open(out_path) as f:
        doc = json.load(f)
    names = {e["name"] for e in doc.get("traceEvents", [])
             if e.get("ph") == "X"}
    return n_events, names


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--samples", type=int, default=8192)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--workers", default="0,1,2,4,8",
                    help="comma-separated num_workers sweep (0 = baseline)")
    ap.add_argument("--work", type=int, default=384,
                    help="preprocess matmul size (CPU cost per sample)")
    ap.add_argument("--trace-out", default=os.path.join(
        tempfile.gettempdir(), "paddle_tpu.input.trace.json"))
    ap.add_argument("--smoke", action="store_true",
                    help="seconds-scale run + invariant asserts (CI)")
    args = ap.parse_args(argv)
    workers = [int(w) for w in args.workers.split(",")]
    if args.smoke:
        args.samples = min(args.samples, 768)
        workers = [0, 4]

    print(f"samples={args.samples} batch_size={args.batch_size} "
          f"work={args.work}x{args.work} (single-threaded BLAS)")
    print(f"{'num_workers':>12}{'samples/s':>12}{'speedup':>9}  stream")
    base_rate = None
    rates = {}
    digests = {}
    for w in workers:
        rate, count, digest = run_loader(
            args.samples, args.batch_size, w, args.work, digest=True)
        rates[w] = rate
        digests[w] = digest
        if base_rate is None:
            base_rate = rate
        print(f"{w:>12}{rate:>12.0f}{rate / base_rate:>8.2f}x  "
              f"{digest[:12]}")

    n_events, span_names = capture_trace(
        args.trace_out, min(args.samples, 512), args.batch_size, args.work)
    dataio_spans = sorted(n for n in span_names if n.startswith("dataio::"))
    print(f"\ntrace: {args.trace_out} ({n_events} events); "
          f"dataio spans: {dataio_spans}")

    if args.smoke:
        _smoke_asserts(args, workers, rates, digests, dataio_spans)
        print("BENCH_INPUT_SMOKE_OK")
    return 0


def _smoke_asserts(args, workers, rates, digests, dataio_spans):
    from paddle_tpu.observability import registry

    # 1. determinism: every worker count produced the identical stream
    uniq = set(digests.values())
    assert len(uniq) == 1, f"batch streams differ across workers: {digests}"

    # 2. throughput: >= 2x over the single-thread DataLoader at 4 workers
    speedup = rates[4] / rates[0]
    print(f"speedup at num_workers=4: {speedup:.2f}x")
    assert speedup >= 2.0, (
        f"num_workers=4 speedup {speedup:.2f}x < 2x over single-thread "
        f"baseline ({rates[0]:.0f} -> {rates[4]:.0f} samples/s)"
    )

    # 3. observability: dataio spans in the Chrome trace, queue gauges +
    # wait histograms in the one registry
    for required in ("dataio::transform", "dataio::device_put"):
        assert required in dataio_spans, (required, dataio_spans)
    snap = registry().snapshot()
    for family in ("dataio_queue_depth", "dataio_producer_wait_seconds",
                   "dataio_consumer_wait_seconds"):
        assert family in snap, (family, sorted(snap))


if __name__ == "__main__":
    sys.exit(main())
