"""Sharded-embedding engine benchmark: lookups/s vs hot-cache ratio,
dedup on/off, with the correctness gates the engine's contracts promise.

Streams a zipfian CTR id workload (the realistic shape: a hot head that
should live on device, a cold tail that should overflow to host RAM)
through ``EmbeddingEngine.prepare_feed`` + a compiled
``sharded_embedding`` train step at several cache capacities, measuring
end-to-end lookups/s and the measured hit rate per config.

``--smoke`` (fast tier, tests/test_embedding.py) shrinks the workload
and ASSERTS the engine's promises instead of trusting them:

  * bit-identical per-step embedding outputs AND final table values
    across every cache configuration (eviction traffic included);
  * a non-trivial measured hit rate on the zipfian stream;
  * HLO dedup evidence: one slab gather moving U_pad < n_ids rows, and
    a firing dedup-off control.

Prints one JSON report (also written to --out); tools/ convention of
bench_input.py / bench_checkpoint.py. EMBEDDING_EVIDENCE_r08.json is
this report at the pinned smoke config, gated by
test_embedding_evidence_r08_committed.
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np


def zipf_batches(steps, batch, ids_per_slot, id_space, seed=0):
    """Zipfian id stream: ranks drawn s=1.2, mapped through a hash so
    hot ids are spread over the space (not 0..k)."""
    from paddle_tpu.embedding.table import splitmix64

    rng = np.random.RandomState(seed)
    out = []
    for _ in range(steps):
        ranks = rng.zipf(1.2, size=(batch, ids_per_slot)).astype(np.uint64)
        ids = splitmix64(ranks) % np.uint64(id_space)
        out.append(ids.astype(np.int64))
    return out


def build(capacity, ep, dim, s, name="bench", lr=0.5, seed=3):
    import paddle_tpu as fluid

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        ids = fluid.data("ids", shape=[-1, s], dtype="int64")
        y = fluid.data("y", shape=[-1, s, dim], dtype="float32")
        emb = fluid.layers.sharded_embedding(
            ids, dim, capacity=capacity, ep=ep, name=name,
            init_range=0.05, lr=lr, seed=seed,
        )
        loss = fluid.layers.mean(fluid.layers.square(
            fluid.layers.elementwise_sub(emb, y)
        ))
        fluid.optimizer.SGD(learning_rate=lr).minimize(loss)
    return main, startup, emb, loss


def run_config(batches, capacity, ep, dim, dedup, fetch_emb=False):
    """Train the stream under one cache config; returns timing, stats,
    per-step fetched embeddings (optional), and the final value map."""
    import paddle_tpu as fluid
    from paddle_tpu.embedding import EmbeddingEngine

    s = batches[0].shape[1]
    main, startup, emb, loss = build(capacity, ep, dim, s)
    exe = fluid.Executor(fluid.CPUPlace())
    sc = fluid.Scope()
    outs, n_ids = [], 0
    with fluid.scope_guard(sc):
        exe.run(startup)
        eng = EmbeddingEngine(scope=sc)
        rngy = np.random.RandomState(7)
        ys = [rngy.randn(b.shape[0], s, dim).astype("float32")
              for b in batches]
        # warm the compile caches outside the timed loop
        feed0 = {"ids": batches[0], "y": ys[0]}
        eng.prepare_feed(main, dict(feed0), dedup=dedup, train=False)
        fetches = [emb, loss] if fetch_emb else [loss]
        t0 = time.perf_counter()
        for bi, (b, y) in enumerate(zip(batches, ys)):
            feed = {"ids": b, "y": y}
            eng.prepare_feed(main, feed, dedup=dedup)
            out = exe.run(main, feed=feed, fetch_list=fetches)
            if fetch_emb:
                outs.append(np.asarray(out[0]).copy())
            n_ids += b.size
        dt = time.perf_counter() - t0
        eng.flush()
        rt = eng.tables["bench"]
        stats = rt.stats()
        values = {i: r.copy() for sh in rt.store._shards
                  for i, r in sh.items()}
        eng.close()
    return {
        "capacity": capacity,
        "ep": ep,
        "dedup": dedup,
        "seconds": dt,
        "lookups_per_s": n_ids / dt if dt > 0 else 0.0,
        "hit_rate": stats["hit_rate"],
        "evictions": stats["evictions"],
        "store_rows": stats["store_rows"],
    }, outs, values


def dedup_hlo_evidence(dim=8, s=6, capacity=64, ep=2):
    """Lower one step both ways and scan the gathers (gather.py)."""
    import paddle_tpu as fluid
    from paddle_tpu.embedding import EmbeddingEngine
    from paddle_tpu.embedding.gather import dedup_evidence
    from paddle_tpu.utils import hlo as uhlo

    main, startup, emb, loss = build(capacity, ep, dim, s, name="ev")
    exe = fluid.Executor(fluid.CPUPlace())
    sc = fluid.Scope()
    with fluid.scope_guard(sc):
        exe.run(startup)
        eng = EmbeddingEngine(scope=sc)
        rng = np.random.RandomState(0)
        idv = rng.randint(0, 8, (4, s)).astype("int64")
        y = rng.randn(4, s, dim).astype("float32")
        n_ids = idv.size
        feed = {"ids": idv, "y": y}
        eng.prepare_feed(main, feed)
        on = dedup_evidence(
            uhlo.lower_program_step(
                main, feed, [loss], scope=sc).as_text(),
            (capacity, dim), n_ids,
        )
        feed2 = {"ids": idv, "y": y}
        eng.prepare_feed(main, feed2, dedup=False)
        off = dedup_evidence(
            uhlo.lower_program_step(
                main, feed2, [loss], scope=sc).as_text(),
            (capacity, dim), n_ids,
        )
        eng.close()
    return on, off


def main():
    ap = argparse.ArgumentParser("sharded embedding engine bench")
    ap.add_argument("--smoke", action="store_true",
                    help="small workload + hard asserts (fast tier)")
    ap.add_argument("--out", default=None, help="write the JSON here too")
    ap.add_argument("--steps", type=int, default=None)
    ap.add_argument("--batch", type=int, default=None)
    args = ap.parse_args()

    from paddle_tpu.observability import metrics as obs_metrics

    if args.smoke:
        steps, batch, s, dim, id_space, ep = 12, 16, 6, 8, 4096, 2
        ratios = (0.125, 0.5, 1.0)
    else:
        steps, batch, s, dim, id_space, ep = 50, 256, 12, 32, 1 << 20, 4
        ratios = (0.1, 0.25, 0.5, 1.0)
    steps = args.steps or steps
    batch = args.batch or batch

    batches = zipf_batches(steps, batch, s, id_space)
    working_set = len(np.unique(np.concatenate(
        [b.reshape(-1) for b in batches])))
    max_batch_unique = max(len(np.unique(b)) for b in batches)

    def cap_for(ratio):
        # capacity must hold one batch's uniques per shard with slack;
        # round up to an ep multiple
        c = max(int(working_set * ratio), 2 * max_batch_unique)
        return ((c + ep - 1) // ep) * ep

    from paddle_tpu import kernels
    from paddle_tpu.kernels.embedding import admission_roundtrip_counter

    rt0 = admission_roundtrip_counter().value
    configs, outputs, valuemaps = [], [], []
    for ratio in ratios:
        rep, outs, values = run_config(
            batches, cap_for(ratio), ep, dim, dedup=True, fetch_emb=True)
        rep["hot_ratio"] = ratio
        configs.append(rep)
        outputs.append(outs)
        valuemaps.append(values)
    device_admission_roundtrips = admission_roundtrip_counter().value - rt0
    # legacy-path control: the smallest config re-run with
    # PADDLE_TPU_KERNELS=off must produce BIT-identical training through
    # the host capacity-slab round-trip (and the round-trip counter must
    # fire — the zero above proves something)
    with kernels.scoped_mode("off"):
        _rep_leg, outs_legacy, values_legacy = run_config(
            batches, cap_for(ratios[0]), ep, dim, dedup=True,
            fetch_emb=True)
    legacy_roundtrips = (admission_roundtrip_counter().value - rt0
                         - device_admission_roundtrips)
    legacy_bit_identical = all(
        np.array_equal(a, b) for a, b in zip(outputs[0], outs_legacy)
    ) and set(values_legacy) == set(valuemaps[0]) and all(
        np.array_equal(valuemaps[0][i], values_legacy[i])
        for i in values_legacy
    )
    # dedup-off control at the largest cache
    rep_off, outs_off, values_off = run_config(
        batches, cap_for(ratios[-1]), ep, dim, dedup=False, fetch_emb=True)
    rep_off["hot_ratio"] = ratios[-1]
    configs.append(rep_off)

    # bit-exactness across every CACHE configuration (the engine's
    # write-back contract); the dedup-off control is numerically
    # equivalent only to summation order (segment-sum vs per-occurrence
    # scatter), so it gets an allclose bound, not a bit gate
    ref = outputs[0]
    bit_identical = all(
        all(np.array_equal(a, b) for a, b in zip(ref, outs))
        for outs in outputs[1:]
    ) and all(
        set(vm) == set(valuemaps[0])
        and all(np.array_equal(valuemaps[0][i], vm[i]) for i in vm)
        for vm in valuemaps[1:]
    )
    dedup_off_max_diff = max(
        (float(np.max(np.abs(a - b))) for a, b in zip(ref, outs_off)),
        default=0.0,
    )

    ev_on, ev_off = dedup_hlo_evidence(dim=dim, s=s)
    reg = obs_metrics.registry()
    gauges = {}
    for fam in ("embedding_cache_hits_total", "embedding_cache_misses_total",
                "embedding_cache_evictions_total", "embedding_cache_occupancy",
                "embedding_staleness_seconds", "embedding_store_rows"):
        total = 0
        for m in reg.collect():
            if m.name == fam:
                total += m.value
        gauges[fam] = total

    smallest = configs[0]
    report = {
        "workload": {
            "steps": steps, "batch": batch, "ids_per_slot": s, "dim": dim,
            "id_space": id_space, "working_set": working_set, "ep": ep,
        },
        "configs": configs,
        "dedup_evidence": ev_on,
        "dedup_off_control": ev_off,
        "cache_hit_gauges": gauges,
        "smoke": {
            "bit_identical_across_configs": bool(bit_identical),
            "dedup_off_max_abs_diff": dedup_off_max_diff,
            "hit_rate": smallest["hit_rate"],
            "device_admission_roundtrips": int(device_admission_roundtrips),
            "legacy_admission_roundtrips": int(legacy_roundtrips),
            "legacy_path_bit_identical": bool(legacy_bit_identical),
        },
    }
    if args.smoke:
        assert bit_identical, (
            "lookup results diverged across cache configurations"
        )
        assert device_admission_roundtrips == 0, (
            "on-device admission still round-tripped the capacity slab "
            f"through host numpy {device_admission_roundtrips}x"
        )
        assert legacy_roundtrips > 0, (
            "legacy control never fired the round-trip counter — the "
            "zero above proves nothing"
        )
        assert legacy_bit_identical, (
            "device admission drifted from the legacy host path"
        )
        assert dedup_off_max_diff < 1e-6, (
            f"dedup on/off drifted past summation-order noise: "
            f"{dedup_off_max_diff}"
        )
        assert smallest["hit_rate"] > 0.3, configs
        assert smallest["evictions"] > 0, (
            "smallest cache saw no evictions — the invariance claim "
            "was not exercised"
        )
        assert ev_on["gathers"] == 1 and ev_on["dedup_saves"], ev_on
        assert ev_off["rows_moved"] >= ev_on["n_ids"], ev_off
        report["smoke"]["asserts"] = "passed"

    txt = json.dumps(report, indent=1, sort_keys=True)
    print(txt)
    if args.out:
        with open(args.out, "w") as f:
            f.write(txt + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
