#!/usr/bin/env python
"""Trace viewer CLI: capture -> export -> summarize top-k spans.

`capture` (default) runs an instrumented workload — a few training steps
(compile + steady-state execute) and a burst of serving requests through
a warmed ServingEngine — under the observability tracer, writes the
Chrome-trace JSON (open in chrome://tracing or ui.perfetto.dev), and
prints the top-k spans by total time. `summarize` re-summarizes an
existing trace JSON without running anything.

`--smoke` is the tier-1 CI hook (wired by tests/test_observability.py):
a seconds-scale capture that asserts the acceptance invariants —
the exported file is valid Chrome-trace JSON with ph/ts/pid/tid on every
event; the timeline contains nested spans covering the compile, execute,
and serving batch-form phases; serving stats, profiler counters, and
executor cache counters are readable from the single metrics registry;
the NaN/Inf sanitizer names the offending op with a user callstack; and
the instrumentation-disabled overhead on the hot execute path is <= 2%.

Usage:
  python tools/trace_view.py [--out /tmp/paddle_tpu.trace.json]
      [--steps 8] [--requests 24] [--top 15] [--smoke]
  python tools/trace_view.py --mode summarize --trace run.trace.json
"""

import argparse
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


# ---------------------------------------------------------------------------
# workloads
# ---------------------------------------------------------------------------

def _build_train(fluid):
    from paddle_tpu.core.ir import Program, program_guard

    main, startup = Program(), Program()
    with program_guard(main, startup):
        x = fluid.data("x", [-1, 16])
        y = fluid.data("y", [-1, 1])
        h = fluid.layers.fc(x, 32, act="relu")
        h = fluid.layers.layer_norm(h, begin_norm_axis=-1)
        pred = fluid.layers.fc(h, 1)
        loss = fluid.layers.mean(
            fluid.layers.square_error_cost(pred, y)
        )
        fluid.optimizer.SGD(learning_rate=0.01).minimize(loss)
    return main, startup, loss


def run_train_steps(steps, trace=True):
    """N optimizer steps on a tiny MLP: step 0 is the traced compile, the
    rest are steady-state cache hits. Returns (exe, per-step seconds)."""
    import paddle_tpu as fluid
    from paddle_tpu.observability import trace_scope

    main, startup, loss = _build_train(fluid)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    rng = np.random.RandomState(0)
    times = []
    with fluid.scope_guard(scope):
        exe.run(startup)
        for i in range(steps):
            feed = {
                "x": rng.randn(8, 16).astype("float32"),
                "y": rng.randn(8, 1).astype("float32"),
            }
            t0 = time.perf_counter()
            if trace:
                with trace_scope("train_step", step=i):
                    exe.run(main, feed=feed, fetch_list=[loss])
            else:
                exe.run(main, feed=feed, fetch_list=[loss])
            times.append(time.perf_counter() - t0)
    return exe, times


def run_serving_burst(requests, tmpdir):
    """Warmed engine + a burst of submits; returns engine stats."""
    import paddle_tpu as fluid
    from paddle_tpu import inference
    from paddle_tpu.core.ir import Program, program_guard
    from paddle_tpu.serving import BucketLattice, ServingEngine

    main, startup = Program(), Program()
    with program_guard(main, startup):
        x = fluid.data("x", [-1, 8])
        h = fluid.layers.fc(x, 16, act="relu")
        pred = fluid.layers.fc(h, 4)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        model_dir = os.path.join(tmpdir, "model")
        fluid.io.save_inference_model(model_dir, ["x"], [pred], exe,
                                      main_program=main)
    config = inference.Config(model_dir)
    config.disable_tpu()
    lattice = BucketLattice.pow2(4, None)
    config.set_serving_buckets(lattice.batch_sizes, lattice.seq_lens)
    rng = np.random.RandomState(1)
    with ServingEngine(config, lattice=lattice, num_replicas=1,
                       max_wait_ms=2.0) as engine:
        resps = [
            engine.submit({"x": rng.randn(int(rng.randint(1, 3)), 8)
                           .astype("float32")})
            for _ in range(requests)
        ]
        for r in resps:
            r.result(timeout=60)
        stats = engine.stats()
    return stats


def run_sanitizer_probe():
    """Deliberately inject a NaN-producing op; returns the raised
    NanInfError (sanitizer must pinpoint op + callstack)."""
    import paddle_tpu as fluid
    from paddle_tpu.core.ir import Program, program_guard
    from paddle_tpu.observability import sanitize_nan_inf
    from paddle_tpu.observability.sanitizer import NanInfError

    main, startup = Program(), Program()
    with program_guard(main, startup):
        x = fluid.data("x", [-1, 4])
        bad = fluid.layers.log(fluid.layers.scale(x, scale=-1.0))
        loss = fluid.layers.mean(bad)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        try:
            with sanitize_nan_inf():
                exe.run(main,
                        feed={"x": np.ones((2, 4), dtype="float32")},
                        fetch_list=[loss])
        except NanInfError as e:
            return e
    return None


# ---------------------------------------------------------------------------
# summaries
# ---------------------------------------------------------------------------

def aggregate_spans(spans):
    """{name: {calls, total_ms, mean_ms, max_ms}} from tracer span dicts."""
    agg = {}
    for s in spans:
        a = agg.setdefault(s["name"], dict(calls=0, total_ms=0.0,
                                           max_ms=0.0))
        ms = s["dur_ns"] / 1e6
        a["calls"] += 1
        a["total_ms"] += ms
        a["max_ms"] = max(a["max_ms"], ms)
    for a in agg.values():
        a["mean_ms"] = a["total_ms"] / a["calls"]
    return agg


def aggregate_chrome_events(events):
    spans = [
        {"name": e["name"], "dur_ns": e.get("dur", 0.0) * 1e3}
        for e in events if e.get("ph") == "X"
    ]
    return aggregate_spans(spans)


def print_topk(agg, k, title):
    print(f"\n== {title}: top {k} spans by total time ==")
    print(f"{'span':<42}{'calls':>7}{'total(ms)':>11}{'mean(ms)':>10}"
          f"{'max(ms)':>10}")
    rows = sorted(agg.items(), key=lambda kv: kv[1]["total_ms"],
                  reverse=True)
    for name, a in rows[:k]:
        print(f"{name:<42}{a['calls']:>7}{a['total_ms']:>11.3f}"
              f"{a['mean_ms']:>10.3f}{a['max_ms']:>10.3f}")


def measure_disabled_overhead(exe_steps_s):
    """Estimate the instrumentation-disabled tax on the hot execute path:
    (disabled spans per step) x (measured per-span disabled cost) over
    the measured steady-state step time."""
    from paddle_tpu.observability import trace_scope, tracing_enabled

    assert not tracing_enabled()
    n = 200_000
    t0 = time.perf_counter()
    for _ in range(n):
        with trace_scope("overhead_probe"):
            pass
    per_span_s = (time.perf_counter() - t0) / n
    # hot compiled path: feed + commit_inputs + execute + fetch spans,
    # one cache-hit counter inc (counted as one span-equivalent)
    spans_per_step = 5
    step_s = min(exe_steps_s) if exe_steps_s else 1.0
    frac = spans_per_step * per_span_s / step_s
    return per_span_s, frac


# ---------------------------------------------------------------------------
# modes
# ---------------------------------------------------------------------------

def capture(args):
    from paddle_tpu import observability as obs
    from paddle_tpu import profiler

    profiler.reset_profiler()
    profiler.start_profiler()
    tracer = obs.enable_tracing()
    sanitizer_err = None
    with tempfile.TemporaryDirectory() as tmp:
        _, step_times = run_train_steps(args.steps)
        serving_stats = run_serving_burst(args.requests, tmp)
        sanitizer_err = run_sanitizer_probe()
    obs.disable_tracing()
    profiler.stop_profiler()
    n_events = obs.export_chrome_trace(args.out)
    spans = tracer.spans()
    agg = aggregate_spans(spans)
    print(f"wrote {args.out}: {n_events} trace events, "
          f"{len(spans)} spans, {len(tracer.instants())} instants")
    print_topk(agg, args.top, "captured run (train + serving)")
    print(f"\nserving: {serving_stats['completed']} completed, "
          f"cache_hit_rate={serving_stats['cache_hit_rate']}, "
          f"batch occupancy={serving_stats['avg_batch_occupancy']:.2f}")
    if sanitizer_err is not None:
        first_line = str(sanitizer_err).splitlines()[0]
        print(f"sanitizer probe: {first_line}")

    if args.smoke:
        _smoke_asserts(args, spans, agg, serving_stats, sanitizer_err,
                       step_times)
        print("TRACE_SMOKE_OK")
    return 0


def _smoke_asserts(args, spans, agg, serving_stats, sanitizer_err,
                   step_times):
    from paddle_tpu.observability import registry

    # 1. valid Chrome-trace JSON with the required keys on every event
    with open(args.out) as f:
        doc = json.load(f)
    assert "traceEvents" in doc and doc["traceEvents"], "empty trace"
    for ev in doc["traceEvents"]:
        assert "ph" in ev and "pid" in ev and "tid" in ev, ev
        if ev["ph"] in ("X", "i"):
            assert "ts" in ev, ev
        if ev["ph"] == "X":
            assert "dur" in ev, ev

    # 2. nested spans covering compile, execute, serving batch-form
    for required in ("executor::trace_compile_execute", "executor::execute",
                     "executor::feed", "serving::batch_form",
                     "serving::batch_run", "predictor::execute",
                     "predictor::aot_compile", "train_step"):
        assert required in agg, (required, sorted(agg))
    by_name = {}
    for s in spans:
        by_name.setdefault(s["name"], []).append(s)
    # executor spans nest under the train_step wrapper...
    assert all(s["depth"] >= 1 for s in by_name["executor::execute"])
    # ...and the serving predictor execution nests inside the batch run
    assert any(s["depth"] >= 1 for s in by_name["predictor::execute"])
    assert all(s["depth"] == 0 for s in by_name["train_step"])

    # 3. one registry: serving + executor + predictor + profiler series
    snap = registry().snapshot()
    for family in ("serving_admitted_total", "serving_run_seconds",
                   "executor_cache_hits_total",
                   "executor_cache_misses_total",
                   "predictor_cache_hits_total", "profiler_counter_total",
                   "sanitizer_violations_total"):
        assert family in snap, (family, sorted(snap))
    assert serving_stats["completed"] == args.requests, serving_stats

    # 4. sanitizer pinpoints the injected NaN op with user callstack
    assert sanitizer_err is not None, "sanitizer did not fire"
    assert sanitizer_err.op_type == "log", sanitizer_err.op_type
    assert sanitizer_err.op_callstack, "no user callstack on NaN error"

    # 5. disabled-instrumentation overhead on the hot execute path <= 2%
    per_span_s, frac = measure_disabled_overhead(step_times)
    print(f"disabled span cost: {per_span_s * 1e9:.0f} ns; "
          f"hot-path overhead estimate: {frac * 100:.3f}%")
    assert frac <= 0.02, f"disabled overhead {frac:.4f} > 2%"

    # 6. the exported text exposition parses as prometheus-ish lines
    text = registry().to_text()
    assert "# TYPE serving_run_seconds histogram" in text
    assert "executor_cache_hits_total" in text


def summarize(args):
    with open(args.trace) as f:
        doc = json.load(f)
    agg = aggregate_chrome_events(doc.get("traceEvents", []))
    print_topk(agg, args.top, args.trace)
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--mode", choices=("capture", "summarize"),
                    default="capture")
    ap.add_argument("--out", default=os.path.join(
        tempfile.gettempdir(), "paddle_tpu.trace.json"))
    ap.add_argument("--trace", help="existing trace JSON (summarize mode)")
    ap.add_argument("--steps", type=int, default=8)
    ap.add_argument("--requests", type=int, default=24)
    ap.add_argument("--top", type=int, default=15)
    ap.add_argument("--smoke", action="store_true",
                    help="seconds-scale capture + invariant asserts (CI)")
    args = ap.parse_args(argv)
    if args.mode == "summarize":
        if not args.trace:
            ap.error("--mode summarize needs --trace")
        return summarize(args)
    if args.smoke:
        args.steps, args.requests = 6, 16
    return capture(args)


if __name__ == "__main__":
    sys.exit(main())
