"""Timing-methodology calibration for the axon TPU backend.

Times a chained jitted matmul with known FLOPs under several sync styles and
prints implied TFLOP/s for each. If any style implies > peak (394 TF/s on
v5e), that style under-waits and must not be used by bench.py.

Measured on the axon tunnel (2026-07, TPU v5 lite):
  A chained+block_until_ready   0.19 ms/step  2857 TF/s  -> UNDER-WAITS (7x peak)
  B chained+np.asarray(16MB)    3020 ms/step  0.2 TF/s   -> tunnel transfer-bound
  C independent+block(last)     4.29 ms/step  128 TF/s   -> under-waits too
  D per-step block              74 ms/step    7.4 TF/s   -> RTT-bound
  G scalar fetch RTT            ~70 ms
  E fori_loop x50 + scalar      162.6 TF/s    -> TRUE device throughput
  F chained dispatch + scalar   161.8 TF/s    -> matches E: the methodology
Conclusion: dispatch the step loop async, sync ONCE by np.asarray of a
scalar output (bench.py does exactly this).
"""

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

N = 4096
STEPS = 20
FLOPS_PER_STEP = 2 * N * N * N * 4  # 4 matmuls


@jax.jit
def step(x, w):
    for _ in range(4):
        x = jnp.tanh(x @ w)
    return x


def main():
    dev = jax.devices()[0]
    print("device:", dev, file=sys.stderr)
    key = jax.random.PRNGKey(0)
    x = jax.device_put(jax.random.normal(key, (N, N), jnp.bfloat16), dev)
    w = jax.device_put(jax.random.normal(key, (N, N), jnp.bfloat16), dev)

    # warmup/compile
    out = step(x, w)
    jax.block_until_ready(out)

    # style A: chained, block_until_ready on final output
    t0 = time.perf_counter()
    y = x
    for _ in range(STEPS):
        y = step(y, w)
    jax.block_until_ready(y)
    dt = time.perf_counter() - t0
    print(f"A chained+block_until_ready: {1e3*dt/STEPS:.2f} ms/step "
          f"{STEPS*FLOPS_PER_STEP/dt/1e12:.1f} TF/s")

    # style B: chained, np.asarray on final output
    t0 = time.perf_counter()
    y = x
    for _ in range(STEPS):
        y = step(y, w)
    _ = np.asarray(y)
    dt = time.perf_counter() - t0
    print(f"B chained+np.asarray:        {1e3*dt/STEPS:.2f} ms/step "
          f"{STEPS*FLOPS_PER_STEP/dt/1e12:.1f} TF/s")

    # style C: independent steps (no chaining), block on last
    t0 = time.perf_counter()
    outs = None
    for _ in range(STEPS):
        outs = step(x, w)
    jax.block_until_ready(outs)
    dt = time.perf_counter() - t0
    print(f"C independent+block(last):   {1e3*dt/STEPS:.2f} ms/step "
          f"{STEPS*FLOPS_PER_STEP/dt/1e12:.1f} TF/s")

    # style D: per-step block (fully sync)
    t0 = time.perf_counter()
    y = x
    for _ in range(STEPS):
        y = step(y, w)
        jax.block_until_ready(y)
    dt = time.perf_counter() - t0
    print(f"D per-step block:            {1e3*dt/STEPS:.2f} ms/step "
          f"{STEPS*FLOPS_PER_STEP/dt/1e12:.1f} TF/s")

    # --- second stage: find the TRUE device throughput ------------------

    @jax.jit
    def scalar_of(z):
        return jnp.sum(z.astype(jnp.float32))

    # style G: RTT of fetching a trivial scalar (tunnel round-trip)
    _ = np.asarray(scalar_of(x))
    t0 = time.perf_counter()
    for _ in range(5):
        _ = np.asarray(scalar_of(x))
    rtt = (time.perf_counter() - t0) / 5
    print(f"G scalar fetch RTT:          {1e3*rtt:.1f} ms")

    # style E: K iterations inside ONE jit, scalar fetch -> ground truth
    INNER = 50

    @jax.jit
    def many(z, wz):
        def body(_, y):
            for _ in range(4):
                y = jnp.tanh(y @ wz)
            return y
        return jnp.sum(jax.lax.fori_loop(0, INNER, body, z)
                       .astype(jnp.float32))

    _ = np.asarray(many(x, w))  # compile + settle
    t0 = time.perf_counter()
    _ = np.asarray(many(x, w))
    dt = time.perf_counter() - t0
    fl = FLOPS_PER_STEP * INNER
    print(f"E fori_loop x{INNER} + scalar fetch: {1e3*dt:.0f} ms total "
          f"{fl/dt/1e12:.1f} TF/s")

    # style F: executor-style chained dispatch, single final scalar fetch
    _ = np.asarray(scalar_of(step(x, w)))
    t0 = time.perf_counter()
    y = x
    for _ in range(INNER):
        y = step(y, w)
    _ = np.asarray(scalar_of(y))
    dt = time.perf_counter() - t0
    print(f"F chained dispatch x{INNER} + final scalar fetch: "
          f"{1e3*dt/INNER:.2f} ms/step {fl/dt/1e12:.1f} TF/s")

    # --- third stage: PURE-matmul roofline sweep (VERDICT r3 item 2) ----
    # The E/F ground truth chains tanh between matmuls; the tanh (VPU) can
    # cap the MXU. A pure x@w chain over a size sweep measures achievable
    # matmul peak — the denominator that makes "frac_of_roofline"
    # interpretable against the 394 TF/s book number.
    print("\npure-matmul roofline sweep (fori_loop, scalar fetch):")
    for n in (2048, 4096, 8192):
        xs = jax.device_put(
            jax.random.normal(key, (n, n), jnp.bfloat16), dev
        )
        ws = jax.device_put(
            jax.random.normal(key, (n, n), jnp.bfloat16), dev
        )
        inner = max(8, (4096 // n) ** 3 * 50)

        @jax.jit
        def pure(z, wz):
            def body(_, y):
                return y @ wz
            return jnp.sum(
                jax.lax.fori_loop(0, inner, body, z).astype(jnp.float32)
            )

        _ = np.asarray(pure(xs, ws))
        t0 = time.perf_counter()
        _ = np.asarray(pure(xs, ws))
        dt = time.perf_counter() - t0
        tf = 2 * n * n * n * inner / dt / 1e12
        print(f"  n={n}: x{inner} matmuls, {1e3*dt:.0f} ms, {tf:.1f} TF/s")


if __name__ == "__main__":
    main()
