#!/usr/bin/env python
"""DECODE_EVIDENCE_r13: the paged-decode perf claims, derivable on demand.

Three claims, all reproducible without a TPU (the PR 6/9 discipline —
static analysis + deterministic counters, never wall-clock):

1. **static_hbm** — `analysis/memory.py` peak-HBM of the SAME decode
   program geometry (8 slots, 32k max context, 16 layers) under the
   dense slotted arena (block_size = max_len: the PR 10 design as the
   degenerate paged config) vs a paged pool sized for realistic
   per-request lengths (~2k tokens): the paged arena is a >= 4x
   peak-HBM reduction. Pure static analysis: programs are built (host
   IR only) and analyzed, never compiled.
2. **block_dedup** — a deterministic hand-stepped admission of three
   prompts sharing a full-block prefix: logical rows exceed physical
   rows while live (ratio > 1), every generation bit-identical to the
   offline reference (sha256 over all tokens committed).
3. **speculative** — a draft entry with the target's geometry
   (deterministic init => byte-identical weights: the acceptance upper
   bound, measured honestly as such) drives target-steps-per-emitted-
   token <= 0.7 with ZERO retraces after warmup (jit counter-asserted),
   and output tokens byte-equal to target-only decode.

Regenerate: ``python tools/decode_report.py --out DECODE_EVIDENCE_r13.json``
Drift gate: tests/test_decode.py::test_decode_evidence_r13_committed
re-derives every deterministic field live and compares byte-for-byte.
"""

import argparse
import hashlib
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

SPEC_PROMPTS = ([3, 1, 4, 1, 5], [9, 2, 6], [3, 1, 4, 1, 5, 9])
SPEC_MAX_NEW = (12, 10, 12)
DEDUP_PREFIX = [7, 3, 9, 2, 11, 5, 8, 1]       # two full blocks at bs=4


def static_hbm_report():
    """Peak-HBM of the decode program: dense slotted grid vs a paged
    pool sized for ~2k used tokens per slot at 32k max context."""
    from paddle_tpu.analysis.memory import estimate_peak_hbm
    from paddle_tpu.serving.decode import build_decoder_model

    geom = dict(vocab_size=32000, hidden=64, num_layers=16, slots=8,
                max_len=32768)
    paged_blocks = 320          # 8 slots * ~2048 tokens / 64 + headroom
    out = {}
    for tag, kw in (
        ("slotted", dict(block_size=geom["max_len"],
                         num_blocks=geom["slots"])),
        ("paged", dict(block_size=64, num_blocks=paged_blocks)),
    ):
        # fused_attention=False pins the r13 program structure (gather +
        # attention composite) so the committed r13 numbers stay
        # byte-reproducible; the kernel-path story is KERNEL_EVIDENCE_r15
        # (tools/kernel_report.py)
        m = build_decoder_model(name=f"hbm_{tag}", version="1", **geom,
                                fused_attention=False, **kw)
        report = estimate_peak_hbm(
            m.decode_program,
            feed_shapes={n: s for n, s, _d in m.decode_feed_sig()},
            fetch_names=[m.logits_fetch],
        )
        out[tag] = {
            "block_size": m.block_size,
            "num_blocks": m.num_blocks,
            "arena_rows": m.rows,
            "arena_bytes": m.arena_bytes(),
            "persistent_bytes": report.persistent_bytes,
            "peak_intermediate_bytes": report.peak_intermediate_bytes,
            "peak_total_bytes": report.peak_total_bytes,
        }
    out["config"] = dict(geom, assumed_tokens_per_request=2048)
    out["peak_reduction_x"] = round(
        out["slotted"]["peak_total_bytes"]
        / float(out["paged"]["peak_total_bytes"]), 2)
    out["arena_reduction_x"] = round(
        out["slotted"]["arena_bytes"]
        / float(out["paged"]["arena_bytes"]), 2)
    return out


def dedup_report():
    """Hand-stepped (threadless, deterministic) shared-prefix admission:
    the radix tree makes three prompts share physical blocks."""
    from paddle_tpu.serving.decode import GenerationEngine, build_decoder_model

    engine = GenerationEngine(queue_depth=16, breaker_threshold=0)
    entry = engine.register_model(lambda: build_decoder_model(
        vocab_size=32, hidden=8, num_layers=2, slots=4, max_len=32,
        block_size=4, name="ev_dedup", version="1"))
    prompts = [DEDUP_PREFIX + [4, 6], DEDUP_PREFIX + [13], DEDUP_PREFIX + [4, 6]]
    refs = [entry.offline_decode(p, 6) for p in prompts]
    resps = [engine.submit(p, max_new_tokens=6) for p in prompts]
    assert entry._admit_free_slots() == 3
    mid = entry.block_pool.stats()
    for _ in range(32):
        if all(r.done() for r in resps):
            break
        entry._step()
    outs = [[int(t) for t in r.result(timeout=60)["tokens"]] for r in resps]
    done = entry.block_pool.stats()
    digest = hashlib.sha256(
        json.dumps(outs, sort_keys=True).encode()).hexdigest()
    return {
        "block_size": 4,
        "prompts": prompts,
        "rows_logical": mid["rows_logical"],
        "rows_live": mid["rows_live"],
        "dedup_ratio": round(mid["dedup_ratio"], 4),
        "radix_hits": mid["radix_hits"],
        "cow_copies": done["cow_copies"],
        "bit_identical": outs == refs,
        "tokens_sha256": digest,
    }


def spec_report():
    """Speculative decoding, deterministic: byte-identical draft (the
    acceptance upper bound), fixed prompts, counted target forwards."""
    from paddle_tpu.observability import metrics as obs_metrics
    from paddle_tpu.serving.decode import GenerationEngine, build_decoder_model

    def jits():
        m = obs_metrics.registry().get("lowering_jit_total")
        return int(m.value) if m is not None else 0

    geom = dict(vocab_size=32, hidden=8, num_layers=2, slots=4, max_len=32,
                block_size=4)
    engine = GenerationEngine(queue_depth=16, breaker_threshold=0)
    tgt = engine.register_model(lambda: build_decoder_model(
        name="ev_spec_t", version="1", **geom))
    engine.register_model(lambda: build_decoder_model(
        name="ev_spec_d", version="1", **geom))
    refs = [tgt.offline_decode(p, n)
            for p, n in zip(SPEC_PROMPTS, SPEC_MAX_NEW)]
    j0 = jits()
    engine.start()
    try:
        resps = [engine.submit(p, model="ev_spec_t", max_new_tokens=n,
                               draft_model="ev_spec_d", spec_k=3)
                 for p, n in zip(SPEC_PROMPTS, SPEC_MAX_NEW)]
        outs = [[int(t) for t in r.result(timeout=120)["tokens"]]
                for r in resps]
    finally:
        engine.shutdown()
    st = tgt.stats()
    digest = hashlib.sha256(
        json.dumps(outs, sort_keys=True).encode()).hexdigest()
    return {
        "spec_k": 3,
        "prompts": [list(p) for p in SPEC_PROMPTS],
        "max_new": list(SPEC_MAX_NEW),
        "target_steps": st["spec_target_steps"],
        "emitted_tokens": st["spec_emitted_tokens"],
        "steps_per_token": round(st["spec_steps_per_token"], 4),
        "acceptance_rate": round(st["spec_acceptance_rate"], 4),
        "retraces_after_warmup": jits() - j0,
        "bit_identical": outs == refs,
        "tokens_sha256": digest,
    }


def build_evidence():
    return {
        "round": 13,
        "static_hbm": static_hbm_report(),
        "block_dedup": dedup_report(),
        "speculative": spec_report(),
    }


def check(evidence):
    """The acceptance gates; raises AssertionError with the failing
    claim."""
    hbm = evidence["static_hbm"]
    assert hbm["peak_reduction_x"] >= 4.0, hbm
    dd = evidence["block_dedup"]
    assert dd["dedup_ratio"] > 1.0, dd
    assert dd["bit_identical"], dd
    sp = evidence["speculative"]
    assert sp["steps_per_token"] <= 0.7, sp
    assert sp["retraces_after_warmup"] == 0, sp
    assert sp["bit_identical"], sp


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", default=None,
                    help="write the evidence JSON here")
    args = ap.parse_args(argv)
    evidence = build_evidence()
    check(evidence)
    text = json.dumps(evidence, indent=1, sort_keys=True)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text + "\n")
        print(f"wrote {args.out}")
    else:
        print(text)
    print("DECODE_EVIDENCE_OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
