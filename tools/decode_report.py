#!/usr/bin/env python
"""DECODE_EVIDENCE_r13: the paged-decode perf claims, derivable on demand.

Three claims, all reproducible without a TPU (the PR 6/9 discipline —
static analysis + deterministic counters, never wall-clock):

1. **static_hbm** — `analysis/memory.py` peak-HBM of the SAME decode
   program geometry (8 slots, 32k max context, 16 layers) under the
   dense slotted arena (block_size = max_len: the PR 10 design as the
   degenerate paged config) vs a paged pool sized for realistic
   per-request lengths (~2k tokens): the paged arena is a >= 4x
   peak-HBM reduction. Pure static analysis: programs are built (host
   IR only) and analyzed, never compiled.
2. **block_dedup** — a deterministic hand-stepped admission of three
   prompts sharing a full-block prefix: logical rows exceed physical
   rows while live (ratio > 1), every generation bit-identical to the
   offline reference (sha256 over all tokens committed).
3. **speculative** — a draft entry with the target's geometry
   (deterministic init => byte-identical weights: the acceptance upper
   bound, measured honestly as such) drives target-steps-per-emitted-
   token <= 0.7 with ZERO retraces after warmup (jit counter-asserted),
   and output tokens byte-equal to target-only decode.

Regenerate: ``python tools/decode_report.py --out DECODE_EVIDENCE_r13.json``
Drift gate: tests/test_decode.py::test_decode_evidence_r13_committed
re-derives every deterministic field live and compares byte-for-byte.

``--gen`` instead derives **GEN_EVIDENCE_r17** — the generation-modes
claims (ISSUE 17), same discipline (deterministic counters + committed
streams, no wall-clock):

1. **sampled** — committed-threefry sampling is bit-identical to the
   offline whole-sequence reference under TWO shuffled admission orders.
2. **beam** — slot-based COW beam search emits the offline beam
   reference's ranked hypotheses byte-for-byte; fork/prune counters and
   block-pool conservation are recorded.
3. **grammar** — regex- and JSON-schema-constrained decode conforms to
   its own DFA (fullmatch / json.loads) and matches the offline masked
   reference; masks ride the DEC_MASK data feed.
4. **spec_sampled** — rejection-rule speculative decoding under a
   non-greedy policy realizes EXACTLY the target-only sampled stream.
5. **draft_kv** — draft-KV slot proposals keep target steps-per-token
   at the PR 13 replay baseline (proposals are bit-identical) while the
   draft does O(1) work per token, zero fallbacks.
6. **retraces_after_warmup** — every mode above, on one warmed engine,
   compiles NOTHING (one jit counter across all legs).

Regenerate: ``python tools/decode_report.py --gen --out GEN_EVIDENCE_r17.json``
Drift gate: tests/test_generate.py::test_gen_evidence_r17_committed.
"""

import argparse
import hashlib
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

SPEC_PROMPTS = ([3, 1, 4, 1, 5], [9, 2, 6], [3, 1, 4, 1, 5, 9])
SPEC_MAX_NEW = (12, 10, 12)
DEDUP_PREFIX = [7, 3, 9, 2, 11, 5, 8, 1]       # two full blocks at bs=4


def static_hbm_report():
    """Peak-HBM of the decode program: dense slotted grid vs a paged
    pool sized for ~2k used tokens per slot at 32k max context."""
    from paddle_tpu.analysis.memory import estimate_peak_hbm
    from paddle_tpu.serving.decode import build_decoder_model

    geom = dict(vocab_size=32000, hidden=64, num_layers=16, slots=8,
                max_len=32768)
    paged_blocks = 320          # 8 slots * ~2048 tokens / 64 + headroom
    out = {}
    for tag, kw in (
        ("slotted", dict(block_size=geom["max_len"],
                         num_blocks=geom["slots"])),
        ("paged", dict(block_size=64, num_blocks=paged_blocks)),
    ):
        # fused_attention=False pins the r13 program structure (gather +
        # attention composite) so the committed r13 numbers stay
        # byte-reproducible; the kernel-path story is KERNEL_EVIDENCE_r15
        # (tools/kernel_report.py)
        m = build_decoder_model(name=f"hbm_{tag}", version="1", **geom,
                                fused_attention=False, **kw)
        report = estimate_peak_hbm(
            m.decode_program,
            feed_shapes={n: s for n, s, _d in m.decode_feed_sig()},
            fetch_names=[m.logits_fetch],
        )
        out[tag] = {
            "block_size": m.block_size,
            "num_blocks": m.num_blocks,
            "arena_rows": m.rows,
            "arena_bytes": m.arena_bytes(),
            "persistent_bytes": report.persistent_bytes,
            "peak_intermediate_bytes": report.peak_intermediate_bytes,
            "peak_total_bytes": report.peak_total_bytes,
        }
    out["config"] = dict(geom, assumed_tokens_per_request=2048)
    out["peak_reduction_x"] = round(
        out["slotted"]["peak_total_bytes"]
        / float(out["paged"]["peak_total_bytes"]), 2)
    out["arena_reduction_x"] = round(
        out["slotted"]["arena_bytes"]
        / float(out["paged"]["arena_bytes"]), 2)
    return out


def dedup_report():
    """Hand-stepped (threadless, deterministic) shared-prefix admission:
    the radix tree makes three prompts share physical blocks."""
    from paddle_tpu.serving.decode import GenerationEngine, build_decoder_model

    engine = GenerationEngine(queue_depth=16, breaker_threshold=0)
    entry = engine.register_model(lambda: build_decoder_model(
        vocab_size=32, hidden=8, num_layers=2, slots=4, max_len=32,
        block_size=4, name="ev_dedup", version="1"))
    prompts = [DEDUP_PREFIX + [4, 6], DEDUP_PREFIX + [13], DEDUP_PREFIX + [4, 6]]
    refs = [entry.offline_decode(p, 6) for p in prompts]
    resps = [engine.submit(p, max_new_tokens=6) for p in prompts]
    assert entry._admit_free_slots() == 3
    mid = entry.block_pool.stats()
    for _ in range(32):
        if all(r.done() for r in resps):
            break
        entry._step()
    outs = [[int(t) for t in r.result(timeout=60)["tokens"]] for r in resps]
    done = entry.block_pool.stats()
    digest = hashlib.sha256(
        json.dumps(outs, sort_keys=True).encode()).hexdigest()
    return {
        "block_size": 4,
        "prompts": prompts,
        "rows_logical": mid["rows_logical"],
        "rows_live": mid["rows_live"],
        "dedup_ratio": round(mid["dedup_ratio"], 4),
        "radix_hits": mid["radix_hits"],
        "cow_copies": done["cow_copies"],
        "bit_identical": outs == refs,
        "tokens_sha256": digest,
    }


def spec_report():
    """Speculative decoding, deterministic: byte-identical draft (the
    acceptance upper bound), fixed prompts, counted target forwards."""
    from paddle_tpu.observability import metrics as obs_metrics
    from paddle_tpu.serving.decode import GenerationEngine, build_decoder_model

    def jits():
        m = obs_metrics.registry().get("lowering_jit_total")
        return int(m.value) if m is not None else 0

    geom = dict(vocab_size=32, hidden=8, num_layers=2, slots=4, max_len=32,
                block_size=4)
    engine = GenerationEngine(queue_depth=16, breaker_threshold=0)
    tgt = engine.register_model(lambda: build_decoder_model(
        name="ev_spec_t", version="1", **geom))
    engine.register_model(lambda: build_decoder_model(
        name="ev_spec_d", version="1", **geom))
    refs = [tgt.offline_decode(p, n)
            for p, n in zip(SPEC_PROMPTS, SPEC_MAX_NEW)]
    j0 = jits()
    engine.start()
    try:
        # draft_kv=False pins this leg to the r13 replay-proposal path so
        # the committed bytes (and the code path they certify) are stable;
        # the draft-KV slot path is GEN_EVIDENCE_r17's draft_kv leg
        resps = [engine.submit(p, model="ev_spec_t", max_new_tokens=n,
                               draft_model="ev_spec_d", spec_k=3,
                               draft_kv=False)
                 for p, n in zip(SPEC_PROMPTS, SPEC_MAX_NEW)]
        outs = [[int(t) for t in r.result(timeout=120)["tokens"]]
                for r in resps]
    finally:
        engine.shutdown()
    st = tgt.stats()
    digest = hashlib.sha256(
        json.dumps(outs, sort_keys=True).encode()).hexdigest()
    return {
        "spec_k": 3,
        "prompts": [list(p) for p in SPEC_PROMPTS],
        "max_new": list(SPEC_MAX_NEW),
        "target_steps": st["spec_target_steps"],
        "emitted_tokens": st["spec_emitted_tokens"],
        "steps_per_token": round(st["spec_steps_per_token"], 4),
        "acceptance_rate": round(st["spec_acceptance_rate"], 4),
        "retraces_after_warmup": jits() - j0,
        "bit_identical": outs == refs,
        "tokens_sha256": digest,
    }


def build_evidence():
    return {
        "round": 13,
        "static_hbm": static_hbm_report(),
        "block_dedup": dedup_report(),
        "speculative": spec_report(),
    }


# ---------------------------------------------------------------------------
# GEN_EVIDENCE_r17: the generation-modes claims
# ---------------------------------------------------------------------------

GEN_PROMPTS = ([5, 9, 2, 4, 7], [11, 3, 8], [6, 1, 12, 2, 9, 4, 3], [14, 2])
GEN_MAX_NEW = 6
# 32-symbol vocabulary for the grammar legs; index 0 is the model's EOS
GEN_VOCAB = ["<eos>"] + list("abcdefghijklmnopqrstuvwxyz") + list("01234")
# PR 13's committed speculative baseline (DECODE_EVIDENCE_r13.json):
# target verify forwards per emitted token at spec_k=3. Draft-KV changes
# WHO computes the proposals, not what they are — the target-side ratio
# must not regress.
R13_STEPS_PER_TOKEN = 0.2647


def _jits():
    from paddle_tpu.observability import metrics as obs_metrics
    m = obs_metrics.registry().get("lowering_jit_total")
    return int(m.value) if m is not None else 0


def _counter_delta(before, after, keys):
    return {k: int(after[k]) - int(before[k]) for k in keys}


def gen_modes_report():
    """One warmed engine drives every r17 mode; ONE jit counter spans all
    legs (the zero-retrace claim is joint, not per-mode)."""
    import re

    import numpy as np

    from paddle_tpu.serving.decode import (
        BeamParams,
        CompiledGrammar,
        GenerationEngine,
        SamplingParams,
        build_decoder_model,
    )

    geom = dict(vocab_size=32, hidden=8, num_layers=2, slots=4, max_len=32,
                block_size=4)
    engine = GenerationEngine(queue_depth=32, breaker_threshold=0)
    tgt = engine.register_model(lambda: build_decoder_model(
        name="ev_gen", version="1", eos_id=0, logits_mask=True, **geom))
    engine.register_model(lambda: build_decoder_model(
        name="ev_gen_d", version="1", eos_id=0, **geom))
    sp = SamplingParams(temperature=0.9, top_k=8, top_p=0.95, seed=42)
    sampled_refs = [tgt.offline_decode(p, GEN_MAX_NEW, sampling=sp)
                    for p in GEN_PROMPTS]
    beam_refs = [tgt.offline_beam(p, GEN_MAX_NEW, BeamParams(3))
                 for p in GEN_PROMPTS[:2]]
    g_re = CompiledGrammar.from_regex("ab*c", GEN_VOCAB, eos_id=0)
    g_js = CompiledGrammar.from_json_schema({"type": "boolean"}, GEN_VOCAB,
                                            eos_id=0)
    grammar_refs = [tgt.offline_decode(GEN_PROMPTS[0], 10, grammar=g)
                    for g in (g_re, g_js)]
    spec_sampled_ref = tgt.offline_decode(GEN_PROMPTS[2], GEN_MAX_NEW,
                                          sampling=sp)
    engine.start()
    j0 = _jits()
    out = {}
    try:
        # -- sampled: two shuffled admission orders, both == offline ----
        before = tgt.stats()
        streams = []
        for order_seed in (0, 1):
            order = np.random.RandomState(order_seed).permutation(
                len(GEN_PROMPTS))
            resps = {}
            for i in order:
                resps[int(i)] = engine.submit(
                    GEN_PROMPTS[i], model="ev_gen",
                    max_new_tokens=GEN_MAX_NEW, sampling=sp)
            streams.append([[int(t) for t in resps[i].result(timeout=120)
                             ["tokens"]] for i in range(len(GEN_PROMPTS))])
        out["sampled"] = {
            "params": sp.describe(),
            "prompts": [list(p) for p in GEN_PROMPTS],
            "admission_orders": 2,
            "bit_identical": all(s == sampled_refs for s in streams),
            "tokens_sha256": hashlib.sha256(json.dumps(
                sampled_refs, sort_keys=True).encode()).hexdigest(),
            **_counter_delta(before, tgt.stats(), ("sampled_tokens",)),
        }

        # -- beam: ranked hypotheses byte-equal the offline reference ---
        before = tgt.stats()
        beams = [engine.submit(p, model="ev_gen", beam_width=3,
                               max_new_tokens=GEN_MAX_NEW)
                 .result(timeout=120) for p in GEN_PROMPTS[:2]]
        tokens_ok = all(
            [[int(t) for t in b["tokens"]]] +
            [[int(t) for t in hyp["tokens"]] for hyp in b["beams"]]
            == [list(ref[0][0])] + [list(rt) for rt, _rs in ref]
            for b, ref in zip(beams, beam_refs))
        # engine scores come from decode-path logits, the reference from
        # whole-sequence prefill logits: equal to accumulated float32 ulp
        # (the same argmax-stability budget the r10 greedy contract uses)
        scores_close = all(
            abs(hyp["score"] - rs) <= 1e-5 * max(1.0, abs(rs))
            for b, ref in zip(beams, beam_refs)
            for hyp, (_rt, rs) in zip(b["beams"], ref))
        tgt.block_pool.check_conservation()
        out["beam"] = {
            "width": 3,
            "prompts": [list(p) for p in GEN_PROMPTS[:2]],
            "tokens_bit_identical": tokens_ok,
            "scores_within_1e5": scores_close,
            "conservation_ok": True,
            "tokens_sha256": hashlib.sha256(json.dumps(
                [[list(rt) for rt, _ in ref] for ref in beam_refs],
                sort_keys=True).encode()).hexdigest(),
            **_counter_delta(before, tgt.stats(),
                             ("beam_requests", "beam_forks", "beam_prunes",
                              "beam_finished")),
        }

        # -- grammar: DFA conformance + offline bit-identity ------------
        before = tgt.stats()
        got_re, got_js = [
            [int(t) for t in engine.submit(
                GEN_PROMPTS[0], model="ev_gen", max_new_tokens=10,
                grammar=g).result(timeout=120)["tokens"]]
            for g in (g_re, g_js)]
        text_re = "".join(GEN_VOCAB[t] for t in got_re if t != 0)
        text_js = "".join(GEN_VOCAB[t] for t in got_js if t != 0)
        out["grammar"] = {
            "regex": "ab*c",
            "schema": {"type": "boolean"},
            "emitted": {"regex": text_re, "json": text_js},
            "conforms": bool(re.fullmatch("ab*c", text_re))
            and isinstance(json.loads(text_js), bool),
            "bit_identical": [got_re, got_js] == grammar_refs,
            **_counter_delta(before, tgt.stats(), ("grammar_steps",)),
        }

        # -- spec_sampled: realized stream == target-only sampling ------
        before = tgt.stats()
        got = [int(t) for t in engine.submit(
            GEN_PROMPTS[2], model="ev_gen", max_new_tokens=GEN_MAX_NEW,
            sampling=sp, draft_model="ev_gen_d", spec_k=3)
            .result(timeout=120)["tokens"]]
        d = _counter_delta(before, tgt.stats(),
                           ("spec_accepted_tokens", "spec_proposed_tokens",
                            "spec_draft_kv_fallbacks"))
        out["spec_sampled"] = {
            "spec_k": 3,
            "bit_identical": got == spec_sampled_ref,
            "acceptance_rate": round(
                d["spec_accepted_tokens"]
                / float(max(1, d["spec_proposed_tokens"])), 4),
            "draft_kv_fallbacks": d["spec_draft_kv_fallbacks"],
        }
    finally:
        engine.shutdown()
    out["retraces_after_warmup"] = _jits() - j0
    return out


def draft_kv_report():
    """PR 13's speculative scenario re-run with draft-KV slots: the
    target-side counters (and the streams) must reproduce the committed
    r13 numbers EXACTLY — proposals are bit-identical, only the draft's
    work drops from O(prompt) replay to O(1) slot steps."""
    from paddle_tpu.serving.decode import GenerationEngine, build_decoder_model

    geom = dict(vocab_size=32, hidden=8, num_layers=2, slots=4, max_len=32,
                block_size=4)
    engine = GenerationEngine(queue_depth=16, breaker_threshold=0)
    tgt = engine.register_model(lambda: build_decoder_model(
        name="ev_kv_t", version="1", **geom))
    engine.register_model(lambda: build_decoder_model(
        name="ev_kv_d", version="1", **geom))
    refs = [tgt.offline_decode(p, n)
            for p, n in zip(SPEC_PROMPTS, SPEC_MAX_NEW)]
    engine.start()
    j0 = _jits()
    try:
        resps = [engine.submit(p, model="ev_kv_t", max_new_tokens=n,
                               draft_model="ev_kv_d", spec_k=3)
                 for p, n in zip(SPEC_PROMPTS, SPEC_MAX_NEW)]
        outs = [[int(t) for t in r.result(timeout=120)["tokens"]]
                for r in resps]
    finally:
        engine.shutdown()
    st = tgt.stats()
    emitted = max(1, st["spec_emitted_tokens"])
    return {
        "spec_k": 3,
        "target_steps": st["spec_target_steps"],
        "emitted_tokens": st["spec_emitted_tokens"],
        "steps_per_token": round(st["spec_steps_per_token"], 4),
        "r13_baseline_steps_per_token": R13_STEPS_PER_TOKEN,
        "draft_kv_prefills": st["spec_draft_kv_prefills"],
        "draft_kv_steps": st["spec_draft_kv_steps"],
        "draft_kv_steps_per_token": round(
            st["spec_draft_kv_steps"] / float(emitted), 4),
        "draft_kv_fallbacks": st["spec_draft_kv_fallbacks"],
        "retraces_after_warmup": _jits() - j0,
        "bit_identical": outs == refs,
        "tokens_sha256": hashlib.sha256(json.dumps(
            outs, sort_keys=True).encode()).hexdigest(),
    }


def build_gen_evidence():
    modes = gen_modes_report()
    return {
        "round": 17,
        "modes": modes,
        "draft_kv": draft_kv_report(),
    }


def check_gen(evidence):
    """GEN_EVIDENCE_r17 acceptance gates; raises AssertionError with the
    failing claim."""
    md = evidence["modes"]
    assert md["sampled"]["bit_identical"], md["sampled"]
    assert md["beam"]["tokens_bit_identical"], md["beam"]
    assert md["beam"]["scores_within_1e5"], md["beam"]
    assert md["beam"]["conservation_ok"], md["beam"]
    assert md["beam"]["beam_forks"] > 0, md["beam"]
    assert md["grammar"]["conforms"], md["grammar"]
    assert md["grammar"]["bit_identical"], md["grammar"]
    assert md["spec_sampled"]["bit_identical"], md["spec_sampled"]
    assert md["spec_sampled"]["draft_kv_fallbacks"] == 0, md["spec_sampled"]
    assert md["retraces_after_warmup"] == 0, md
    dk = evidence["draft_kv"]
    assert dk["steps_per_token"] <= R13_STEPS_PER_TOKEN, dk
    assert dk["draft_kv_fallbacks"] == 0, dk
    assert dk["draft_kv_prefills"] == len(SPEC_PROMPTS), dk
    assert dk["retraces_after_warmup"] == 0, dk
    assert dk["bit_identical"], dk


def check(evidence):
    """The acceptance gates; raises AssertionError with the failing
    claim."""
    hbm = evidence["static_hbm"]
    assert hbm["peak_reduction_x"] >= 4.0, hbm
    dd = evidence["block_dedup"]
    assert dd["dedup_ratio"] > 1.0, dd
    assert dd["bit_identical"], dd
    sp = evidence["speculative"]
    assert sp["steps_per_token"] <= 0.7, sp
    assert sp["retraces_after_warmup"] == 0, sp
    assert sp["bit_identical"], sp


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", default=None,
                    help="write the evidence JSON here")
    ap.add_argument("--gen", action="store_true",
                    help="derive GEN_EVIDENCE_r17 (generation modes) "
                         "instead of DECODE_EVIDENCE_r13")
    args = ap.parse_args(argv)
    if args.gen:
        evidence = build_gen_evidence()
        check_gen(evidence)
        tag = "GEN_EVIDENCE_OK"
    else:
        evidence = build_evidence()
        check(evidence)
        tag = "DECODE_EVIDENCE_OK"
    text = json.dumps(evidence, indent=1, sort_keys=True)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text + "\n")
        print(f"wrote {args.out}")
    else:
        print(text)
    print(tag)
    return 0


if __name__ == "__main__":
    sys.exit(main())
