"""Dense vs row-sparse embedding update benchmark (VERDICT r3 item 6).

Times one embedding-regression train step at vocab >= 100k in both forms:
  dense : lookup_table_grad materializes the [V, D] gradient, sgd applies
          p - lr*g over every row (the pre-r4 behavior)
  sparse: sparse_weight_update pass -> sgd_sparse row scatter (SelectedRows
          analog)

Usage: python tools/bench_sparse_embedding.py [vocab] [dim] [tokens]
Prints one JSON line with both times and the speedup.
"""

import json
import sys
import time

import numpy as np


def bench(vocab=100_000, dim=512, tokens=8192, steps=20):
    from paddle_tpu.core.places import ensure_backend_or_cpu

    on_tpu, diag = ensure_backend_or_cpu()
    import paddle_tpu as fluid
    from paddle_tpu.utils.flags import flags

    rng = np.random.RandomState(0)
    ids = rng.randint(0, vocab, (tokens,)).astype("int64")
    y = rng.randn(tokens, dim).astype("float32")
    results = {}
    for sparse in (False, True):
        old = flags.sparse_embedding_update
        flags.sparse_embedding_update = sparse
        try:
            main, startup = fluid.Program(), fluid.Program()
            with fluid.program_guard(main, startup):
                iv = fluid.data("ids", [tokens], dtype="int64")
                yv = fluid.data("y", [tokens, dim])
                emb = fluid.layers.embedding(
                    iv, size=[vocab, dim],
                    param_attr=fluid.ParamAttr(
                        name=f"w_{sparse}",
                        initializer=fluid.initializer.NormalInitializer(
                            0, 0.1
                        ),
                    ),
                )
                loss = fluid.layers.mean(fluid.layers.square(
                    fluid.layers.elementwise_sub(emb, yv)
                ))
                fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
        finally:
            flags.sparse_embedding_update = old
        types = [op.type for op in main.global_block().ops]
        assert ("sgd_sparse" in types) == sparse, types
        exe = fluid.Executor(fluid.TPUPlace(0))
        sc = fluid.Scope()
        with fluid.scope_guard(sc):
            exe.run(startup)
            feed = {"ids": ids, "y": y}
            for _ in range(3):  # compile + warm
                out = exe.run(main, feed=feed, fetch_list=[loss],
                              return_numpy=False)
            np.asarray(out[0])
            t0 = time.perf_counter()
            for _ in range(steps):
                out = exe.run(main, feed=feed, fetch_list=[loss],
                              return_numpy=False)
            np.asarray(out[0])  # value-fetch sync (bench.py discipline)
            dt = (time.perf_counter() - t0) / steps
        results["sparse" if sparse else "dense"] = dt * 1000.0
    return {
        "metric": "embedding_update_ms",
        "vocab": vocab,
        "dim": dim,
        "tokens": tokens,
        "device": "tpu" if on_tpu else "cpu",
        "dense_ms": round(results["dense"], 3),
        "sparse_ms": round(results["sparse"], 3),
        "speedup": round(results["dense"] / results["sparse"], 2),
    }


if __name__ == "__main__":
    args = [int(a) for a in sys.argv[1:4]]
    print(json.dumps(bench(*args)))
