"""Microbench: Pallas flash attention (fwd+bwd) vs unfused jnp attention.

Run on the real chip; prints one JSON line per (seq_len, variant) so the
long-sequence scaling of the fused kernel is visible (the round-2 jnp
backward was O(S^2) in HBM and this documents the replacement's win).
"""

import functools
import json
import math
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp


def unfused(q, k, v, causal):
    scale = 1 / math.sqrt(q.shape[-1])
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        S = q.shape[2]
        s = jnp.where(jnp.tril(jnp.ones((S, S), bool))[None, None], s, -1e30)
    p = jax.nn.softmax(s, -1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32)).astype(q.dtype)


def bench(fn, args, iters=20):
    out = jax.block_until_ready(fn(*args))
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters, out


def main():
    from paddle_tpu.ops.pallas.flash_attention import flash_attention

    B, H, D = 4, 12, 64
    causal = True
    for S in (512, 1024, 2048, 4096):
        rng = np.random.RandomState(0)
        q, k, v = (
            jnp.asarray(rng.randn(B, H, S, D).astype(np.float32)).astype(
                jnp.bfloat16
            )
            for _ in range(3)
        )

        def loss_flash(q, k, v):
            return (flash_attention(q, k, v, causal=causal) ** 2).sum()

        def loss_unfused(q, k, v):
            return (unfused(q, k, v, causal) ** 2).sum()

        from paddle_tpu.core.lowering import jit_compile

        grad_flash = jit_compile(jax.grad(loss_flash, argnums=(0, 1, 2)))
        grad_unfused = jit_compile(jax.grad(loss_unfused, argnums=(0, 1, 2)))

        # attention FLOPs fwd+bwd ~ 2 matmuls fwd + 5 bwd (dq,dk,dv,dp,recompute)
        flops = 7 * 2 * B * H * S * S * D * (0.5 if causal else 1.0)
        for name, fn in (("flash_pallas", grad_flash),
                         ("unfused_jnp", grad_unfused)):
            try:
                dt, g = bench(fn, (q, k, v))
                err = None
            except Exception as e:  # OOM at long S for the unfused path
                dt, err = None, str(e)[:160]
            rec = {"seq_len": S, "variant": name}
            if dt is not None:
                rec["ms"] = round(1000 * dt, 2)
                rec["tflops"] = round(flops / dt / 1e12, 1)
            else:
                rec["error"] = err
            print(json.dumps(rec), flush=True)


if __name__ == "__main__":
    main()
