#!/usr/bin/env python
"""Overload evidence: graceful degradation under block-pool pressure.

Emits OVERLOAD_EVIDENCE_r18.json, the committed witness for the r18
robustness contract:

  * **preemption bit-identity** — hand-stepped (no scheduler thread)
    park/resume episodes in every generation mode (greedy, sampled,
    beam, speculative): an undersized block pool forces sessions to
    spill their KV rows to the host tier mid-generation and resume
    later; every finished stream must byte-equal the uninterrupted
    offline reference. Deterministic, so these sections are
    DRIFT-GATED: tests/test_overload.py recomputes them live and any
    divergence from the committed file is a failure.
  * **corruption walk-back** — a parked session's host-tier entry is
    deliberately corrupted; the CRC check must quarantine it and the
    resume must fall back to recomputing the KV from the token history
    (``resume_replays``), still byte-identical.
  * **zero-loss ledger** — a 2x-capacity burst through the same tight
    pool: every ACCEPTED request completes (parks are invisible), the
    accounting identity ``accepted == completed`` holds with zero
    failures, and the full token set digests identically across runs.
  * **brownout ladder** — the BrownoutController replayed over a
    scripted pressure trace: escalation is immediate, de-escalation is
    hysteretic (``hold`` consecutive clear evaluations per level), and
    the exact transition list is committed.
  * **p99-of-admitted** (measured, NOT drift-gated — wall-clock) — the
    p99 latency of admitted requests under the burst stays within a
    bounded multiple of the uncontended baseline.

Usage:
  python tools/overload_report.py [--evidence OVERLOAD_EVIDENCE_r18.json]
      [--json]
"""

import argparse
import hashlib
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

# p99 gate: generous (CPU timing on a shared container) but bounded
P99_RATIO_BOUND = 15.0
P99_FLOOR_S = 2.0

VOCAB, HIDDEN, LAYERS = 32, 8, 1


def _digest(payload):
    return hashlib.sha256(json.dumps(payload).encode()).hexdigest()


def _build(name, slots, num_blocks, max_len=16, block_size=2):
    from paddle_tpu.serving.decode import build_decoder_model

    return build_decoder_model(
        vocab_size=VOCAB, hidden=HIDDEN, num_layers=LAYERS, slots=slots,
        max_len=max_len, block_size=block_size, num_blocks=num_blocks,
        name=name, version="1")


def _drain(entry, resps, iters=600):
    for _ in range(iters):
        if all(r.done() for r in resps):
            return
        entry._iterate()
    raise AssertionError(
        f"hand-stepped drain did not converge in {iters} iterations")


def _leg_greedy(sampling=None):
    """Two sessions against a 12-row pool: both fit alone, not
    together — one parks mid-generation and resumes after the other
    retires. Hand-stepped, so the park/resume schedule is a pure
    function of the code."""
    from paddle_tpu.serving.decode import GenerationEngine

    mode = "greedy" if sampling is None else "sampled"
    engine = GenerationEngine(queue_depth=16, breaker_threshold=0)
    entry = engine.register_model(
        lambda: _build(f"ov_{mode}", slots=2, num_blocks=6))
    prompts = [[1, 2, 3, 4], [5, 6, 7, 8]]
    refs = [entry.offline_decode(p, 6, sampling=sampling)
            for p in prompts]
    resps = [engine.submit(p, max_new_tokens=6, sampling=sampling)
             for p in prompts]
    _drain(entry, resps)
    outs = [[int(t) for t in r.result(timeout=60)["tokens"]]
            for r in resps]
    st = entry.stats()
    engine.shutdown()
    return {
        "mode": mode,
        "requests": len(prompts),
        "parked": st["sessions_parked"],
        "resumed": st["sessions_resumed"],
        "spills": st["host_tier"]["spills"],
        "bit_identical": outs == refs,
        "tokens_digest": _digest(outs),
    }


def _leg_beam():
    """A width-2 beam group and a greedy competitor against a 20-row
    pool: joint demand exceeds it, either party can fit alone — the
    exhausted one parks (the beam group spills PER-HYPOTHESIS, rank
    keyed) and resumes to byte-identical ranked hypotheses."""
    from paddle_tpu.serving.decode import BeamParams, GenerationEngine

    engine = GenerationEngine(queue_depth=16, breaker_threshold=0)
    entry = engine.register_model(
        lambda: _build("ov_beam", slots=3, num_blocks=10))
    comp_prompt, beam_prompt = [1, 2, 3, 4], [5, 6, 7, 8]
    comp_ref = entry.offline_decode(comp_prompt, 8)
    beam_ref = entry.offline_beam(beam_prompt, 6, BeamParams(2))
    comp = engine.submit(comp_prompt, max_new_tokens=8)
    beam = engine.submit(beam_prompt, max_new_tokens=6, beam_width=2)
    _drain(entry, [comp, beam])
    comp_out = [int(t) for t in comp.result(timeout=60)["tokens"]]
    beam_out = [[int(t) for t in h["tokens"]]
                for h in beam.result(timeout=60)["beams"]]
    st = entry.stats()
    engine.shutdown()
    ok = (comp_out == comp_ref
          and beam_out == [list(rt) for rt, _rs in beam_ref])
    return {
        "mode": "beam",
        "requests": 2,
        "parked": st["sessions_parked"],
        "resumed": st["sessions_resumed"],
        "spills": st["host_tier"]["spills"],
        "bit_identical": ok,
        "tokens_digest": _digest([comp_out, beam_out]),
    }


def _leg_spec():
    """A speculative session (no target-arena footprint) decoding
    alongside two greedy competitors whose joint demand oversubscribes
    the pool: the competitors park/resume around it and every stream —
    the speculative one included — stays byte-identical. Only the
    bit-identity half is drift-gated: whether the spec admission kept
    its draft-KV slot depends on the brownout level at admission time,
    which tracks wall-clock queue pressure."""
    from paddle_tpu.serving.decode import GenerationEngine

    engine = GenerationEngine(queue_depth=16, breaker_threshold=0)
    entry = engine.register_model(
        lambda: _build("ov_spec_t", slots=3, num_blocks=8))
    engine.register_model(
        lambda: _build("ov_spec_d", slots=2, num_blocks=16))
    spec_prompt = [3, 1, 3, 1]
    comp_prompts = [[1, 2, 3, 4], [5, 6, 7, 8]]
    spec_ref = entry.offline_decode(spec_prompt, 6)
    comp_refs = [entry.offline_decode(p, 6) for p in comp_prompts]
    comps = [engine.submit(p, max_new_tokens=6, model="ov_spec_t")
             for p in comp_prompts]
    spec = engine.submit(spec_prompt, max_new_tokens=6,
                         model="ov_spec_t", draft_model="ov_spec_d",
                         spec_k=2)
    _drain(entry, comps + [spec])
    comp_outs = [[int(t) for t in r.result(timeout=60)["tokens"]]
                 for r in comps]
    spec_out = [int(t) for t in spec.result(timeout=60)["tokens"]]
    st = entry.stats()
    engine.shutdown()
    ok = comp_outs == comp_refs and spec_out == spec_ref
    return {
        "mode": "spec",
        "requests": 3,
        "parked": st["sessions_parked"],
        "resumed": st["sessions_resumed"],
        "bit_identical": ok,
        "tokens_digest": _digest([spec_out] + comp_outs),
    }


def _leg_corruption():
    """CRC walk-back: park a session, flip one byte of its host-tier
    entry, resume. The tier must quarantine the corrupt entry (a miss,
    never a wrong read) and the resume must recompute the KV from the
    token history — the checkpoint.py quarantine idiom applied to the
    spill tier."""
    from paddle_tpu.serving.decode import GenerationEngine

    engine = GenerationEngine(queue_depth=16, breaker_threshold=0)
    entry = engine.register_model(
        lambda: _build("ov_crc", slots=2, num_blocks=6))
    prompts = [[1, 2, 3, 4], [5, 6, 7, 8]]
    refs = [entry.offline_decode(p, 6) for p in prompts]
    resps = [engine.submit(p, max_new_tokens=6) for p in prompts]
    corrupted = 0
    for _ in range(600):
        if all(r.done() for r in resps):
            break
        if entry._parked and not corrupted:
            for key in entry._parked[0].keys:
                entry._tier.corrupt_entry(key)
                corrupted += 1
        entry._iterate()
    outs = [[int(t) for t in r.result(timeout=60)["tokens"]]
            for r in resps]
    st = entry.stats()
    engine.shutdown()
    return {
        "mode": "corruption_walkback",
        "corrupted_entries": corrupted,
        "corrupt_dropped": st["host_tier"]["corrupt_dropped"],
        "resume_replays": st["resume_replays"],
        "parked": st["sessions_parked"],
        "resumed": st["sessions_resumed"],
        "bit_identical": outs == refs,
        "tokens_digest": _digest(outs),
    }


def _leg_ledger():
    """Zero-loss ledger under a 2x burst: 8 requests against a pool
    that serves 2 at a time, submitted up front and hand-stepped to
    drain. The accounting identity the evidence commits: accepted ==
    completed, failed == 0 — parks and the host tier make overload a
    LATENCY event, never a loss event."""
    from paddle_tpu.serving.decode import GenerationEngine

    engine = GenerationEngine(queue_depth=32, breaker_threshold=0)
    entry = engine.register_model(
        lambda: _build("ov_ledger", slots=2, num_blocks=6))
    prompts = [[(3 * i + j) % VOCAB for j in range(1, 5)]
               for i in range(8)]
    refs = [entry.offline_decode(p, 6) for p in prompts]
    resps = [engine.submit(p, max_new_tokens=6) for p in prompts]
    _drain(entry, resps, iters=1200)
    outs = [[int(t) for t in r.result(timeout=60)["tokens"]]
            for r in resps]
    st = entry.stats()
    engine.shutdown()
    return {
        "accepted": len(resps),
        "completed": st["completed"],
        "failed": st["failed"],
        "lost": len(resps) - st["completed"],
        "bit_identical": outs == refs,
        "tokens_digest": _digest(outs),
    }, {
        "ledger_parked": st["sessions_parked"],
        "ledger_resumed": st["sessions_resumed"],
        "ledger_spills": st["host_tier"]["spills"],
        "ledger_writebacks": st["host_tier"]["writebacks"],
        "ledger_brownout_transitions":
            len(st["brownout"]["transitions"]),
    }


def _leg_brownout():
    """The severity ladder replayed over a scripted pressure trace —
    the controller is clockless and threadless, so the transition list
    is exact: a spike escalates straight to L4, the decay walks down
    one level per ``hold`` clear evaluations, and a value inside the
    hysteresis band (0.72 between exit 0.70 and enter 0.85) holds L3
    without flapping."""
    from paddle_tpu.serving.brownout import BrownoutController

    ctl = BrownoutController()
    trace = (
        [("occupancy", 0.2)] * 2          # quiet
        + [("occupancy", 0.97)]           # spike: straight to L4
        + [("queue_seconds", 0.9)] * 2    # stays hot on a second signal
        + [("occupancy", 0.72)] * 8       # in L3's hysteresis band
        + [("occupancy", 0.3)] * 12       # clear: ladder walks down
    )
    levels = []
    for sig, val in trace:
        levels.append(ctl.step(**{sig: val}))
    return {
        "trace_len": len(trace),
        "levels": levels,
        "peak": max(levels),
        "final": levels[-1],
        "transitions": ctl.snapshot()["transitions"],
        "enter": list(ctl.enter),
        "exit": list(ctl.exit),
        "hold": ctl.hold,
    }


def _p99(samples):
    if not samples:
        return 0.0
    s = sorted(samples)
    return s[min(int(len(s) * 0.99), len(s) - 1)]


def _leg_p99():
    """Wall-clock leg (measured, not drift-gated): p99 of ADMITTED
    requests under the 2x burst vs an uncontended sequential baseline
    through an identical engine. Parking trades latency for loss — the
    trade is only honest if the latency stays bounded."""
    from paddle_tpu.serving.decode import GenerationEngine

    def run(name, burst):
        engine = GenerationEngine(queue_depth=32, breaker_threshold=0)
        engine.register_model(
            lambda: _build(name, slots=2, num_blocks=6))
        engine.start()
        prompts = [[(3 * i + j) % VOCAB for j in range(1, 5)]
                   for i in range(8)]
        lats = []
        shed = 0
        if burst:
            pend = []
            for p in prompts:
                try:
                    pend.append((engine.submit(p, max_new_tokens=6),
                                 time.perf_counter()))
                except Exception:
                    shed += 1
            for r, t0 in pend:
                r.result(timeout=240)
                lats.append(time.perf_counter() - t0)
        else:
            for p in prompts:
                t0 = time.perf_counter()
                engine.submit(p, max_new_tokens=6).result(timeout=240)
                lats.append(time.perf_counter() - t0)
        engine.shutdown()
        return lats, shed

    base, _ = run("ov_p99_base", burst=False)
    over, shed = run("ov_p99_burst", burst=True)
    p99_base, p99_over = _p99(base), _p99(over)
    bound = max(P99_RATIO_BOUND * p99_base, P99_FLOOR_S)
    return {
        "p99_baseline_ms": round(p99_base * 1e3, 1),
        "p99_admitted_ms": round(p99_over * 1e3, 1),
        "p99_bound_ms": round(bound * 1e3, 1),
        "bounded": p99_over <= bound,
        "admitted": len(over),
        "shed": shed,
    }


def deterministic_sections():
    """Everything the drift gate recomputes: hand-stepped, clockless,
    single-threaded. The SAME function backs ``--evidence`` and
    tests/test_overload.py::test_overload_evidence_r18_committed."""
    from paddle_tpu.serving.decode import SamplingParams

    preemption = [
        _leg_greedy(),
        _leg_greedy(SamplingParams(temperature=0.8, top_k=6, seed=7)),
        _leg_beam(),
        _leg_spec(),
    ]
    corruption = _leg_corruption()
    ledger, ledger_measured = _leg_ledger()
    brownout = _leg_brownout()
    # the spec leg's park/resume COUNTS ride on wall-clock brownout
    # state at admission; gate only its schedule-independent half
    gated_preemption = []
    for leg in preemption:
        keep = {"mode", "requests", "bit_identical", "tokens_digest"}
        if leg["mode"] != "spec":
            keep |= {"parked", "resumed", "spills"}
        gated_preemption.append(
            {k: v for k, v in leg.items() if k in keep})
    invariants = {
        "preemption": gated_preemption,
        "corruption": {k: corruption[k] for k in
                       ("mode", "corrupted_entries", "bit_identical",
                        "tokens_digest")},
        "ledger": ledger,
        "brownout": brownout,
    }
    measured = dict(ledger_measured)
    measured["corruption_resume_replays"] = corruption["resume_replays"]
    measured["corruption_corrupt_dropped"] = \
        corruption["corrupt_dropped"]
    measured["spec_parked"] = preemption[3]["parked"]
    measured["spec_resumed"] = preemption[3]["resumed"]
    return invariants, measured


def check_invariants(invariants):
    failures = []

    def check(ok, msg):
        if not ok:
            failures.append(msg)

    for leg in invariants["preemption"]:
        check(leg["bit_identical"],
              f"{leg['mode']}: BIT-IDENTITY VIOLATED across park/resume")
        if "parked" in leg:
            check(leg["parked"] >= 1 and leg["resumed"] >= 1,
                  f"{leg['mode']}: no preemption happened "
                  f"(parked={leg.get('parked')}) — the leg proved "
                  "nothing")
    check(invariants["corruption"]["bit_identical"],
          "corruption walk-back: BIT-IDENTITY VIOLATED")
    check(invariants["corruption"]["corrupted_entries"] >= 1,
          "corruption walk-back: nothing was corrupted")
    led = invariants["ledger"]
    check(led["lost"] == 0 and led["failed"] == 0,
          f"ledger: ZERO-LOSS VIOLATED — accepted {led['accepted']} "
          f"completed {led['completed']} failed {led['failed']}")
    check(led["bit_identical"], "ledger: BIT-IDENTITY VIOLATED")
    bo = invariants["brownout"]
    check(bo["peak"] == 4 and bo["final"] == 0,
          f"brownout: ladder did not traverse L4 and return to L0 "
          f"(peak={bo['peak']} final={bo['final']})")
    check(len(bo["transitions"]) >= 5,
          f"brownout: {len(bo['transitions'])} transitions — the "
          "scripted trace should walk up once and down four times")
    return failures


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--evidence", metavar="OUT.json",
                    help="write the committed overload evidence file")
    ap.add_argument("--json", action="store_true", dest="as_json")
    ap.add_argument("--skip-p99", action="store_true",
                    help="deterministic sections only (the drift-gated "
                         "half)")
    args = ap.parse_args(argv)

    invariants, measured = deterministic_sections()
    failures = check_invariants(invariants)
    if not args.skip_p99:
        p99 = _leg_p99()
        measured.update(p99)
        if not p99["bounded"]:
            failures.append(
                f"p99-of-admitted {p99['p99_admitted_ms']}ms exceeds "
                f"bound {p99['p99_bound_ms']}ms")
    payload = {
        "issue": 18,
        "generated_by": ("python tools/overload_report.py --evidence "
                         "OVERLOAD_EVIDENCE_r18.json"),
        "drift_gates": [
            "tests/test_overload.py::"
            "test_overload_evidence_r18_committed",
        ],
        "invariants": invariants,
        # informational: wall-clock / schedule-dependent, NOT gated
        "measured": measured,
    }
    if args.evidence:
        with open(args.evidence, "w") as f:
            json.dump(payload, f, indent=1, sort_keys=True)
            f.write("\n")
        led = invariants["ledger"]
        print(f"wrote {args.evidence}: lost={led['lost']} "
              f"modes_bit_identical="
              f"{all(l['bit_identical'] for l in invariants['preemption'])} "
              f"brownout_peak={invariants['brownout']['peak']}")
    if args.as_json or not args.evidence:
        print(json.dumps(payload, indent=None if args.as_json else 1))
    if failures:
        for f in failures:
            print(f"OVERLOAD FAIL: {f}", file=sys.stderr)
        return 1
    print("OVERLOAD_REPORT_OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
