#!/bin/bash
# TPU measurement pipeline: poll the axon backend; on the FIRST successful
# probe run the full on-chip measurement suite back-to-back. Designed for
# rounds where the chip tunnel stalls (round 3 + round 4 both lost their
# bench window to it): start this at round begin, let it capture whenever
# the pool grants a chip.
#
# Usage: nohup tools/tpu_capture.sh [logfile] &
# Context (round 4): the axon relay (127.0.0.1, AXON_LOOPBACK_RELAY=1) was
# reachable all round but the remote pool never granted a chip — every
# jax.devices() probe hung until timeout. Nothing is fixable client-side;
# polling until a grant arrives is the only play.
cd "$(dirname "$0")/.."
log=${1:-/tmp/tpu_capture.log}
echo "capture pipeline start $(date)" > "$log"
for i in $(seq 1 200); do
  echo "=== probe $i $(date +%H:%M:%S)" >> "$log"
  timeout 240 python -c "
import jax, numpy as np, jax.numpy as jnp
d = jax.devices(); print('devices', d)
x = jnp.ones((512,512), jnp.bfloat16)
v = np.asarray(x@x); print('ok', float(v[0,0]))
" >> "$log" 2>&1
  if [ $? -eq 0 ]; then
    echo "=== TPU ALIVE $(date +%H:%M:%S) — capturing" >> "$log"
    echo "--- calibrate_timing (incl. pure-matmul roofline sweep)" >> "$log"
    timeout 900 python tools/calibrate_timing.py >> "$log" 2>&1
    echo "--- bench_flash (validates Pallas kernels OUTSIDE interpret)" >> "$log"
    timeout 900 python tools/bench_flash.py >> "$log" 2>&1
    echo "--- bench.py (headline metrics + self-measured roofline)" >> "$log"
    timeout 2400 python bench.py > /tmp/bench_tpu.json 2>>"$log"
    cat /tmp/bench_tpu.json >> "$log"
    echo "--- profile_bench ablation matrix" >> "$log"
    timeout 2400 python tools/profile_bench.py >> "$log" 2>&1
    echo "--- bench_sparse_embedding (sgd_sparse vs dense at vocab 100k)" >> "$log"
    timeout 900 python tools/bench_sparse_embedding.py >> "$log" 2>&1
    echo "--- bench_transformer_infer (big cfg bucketed beam, 37k vocab)" >> "$log"
    timeout 1800 python tools/bench_transformer_infer.py >> "$log" 2>&1
    echo "=== CAPTURE COMPLETE $(date +%H:%M:%S)" >> "$log"
    exit 0
  fi
  sleep 45
done
echo "=== gave up after 200 probes $(date)" >> "$log"
exit 1
