"""Ablation profiler for the headline BERT bench (feeds PROFILE.md).

Runs the same Program/Executor step bench.py times, under a matrix of knobs,
and reports tokens/s + MFU per variant so the step-time budget can be
attributed (the reference attributes per-op time via its profiler,
reference: paddle/fluid/platform/profiler.h:199; on TPU the step is one XLA
computation, so attribution is by ablation + jax.profiler trace instead).

Usage:
  python tools/profile_bench.py [batch] [seq_len]        # ablation table
  PROFILE_TRACE_DIR=/tmp/trace python tools/profile_bench.py  # + xplane trace
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _build(cfg_kwargs, seq_len, use_amp, max_pred=None):
    import paddle_tpu as fluid
    from paddle_tpu.models import bert

    cfg = bert.BertConfig.base()
    for k, v in cfg_kwargs.items():
        setattr(cfg, k, v)
    main, startup, feeds, fetches = bert.build_bert_pretrain(
        cfg, seq_len=seq_len, lr=1e-4, use_amp=use_amp,
        max_predictions_per_seq=max_pred,
    )
    return cfg, main, startup, fetches


def run_variant(name, batch, seq_len, steps=10, use_amp=True,
                trace_dir=None, max_pred=None, rng_impl="threefry",
                **cfg_kwargs):
    import jax

    import paddle_tpu as fluid
    from paddle_tpu.models import bert
    from paddle_tpu.utils.flags import flags

    flags.rng_impl = rng_impl
    cfg, main, startup, fetches = _build(cfg_kwargs, seq_len, use_amp,
                                         max_pred)
    exe = fluid.Executor(fluid.TPUPlace(0))
    exe.run(startup)
    rng = np.random.RandomState(0)
    data = bert.synthetic_batch(
        rng, batch, seq_len, cfg, max_predictions_per_seq=max_pred
    )

    for _ in range(2):  # compile + settle
        out = exe.run(main, feed=data, fetch_list=[fetches[0]],
                      return_numpy=False)
    # value-fetch sync: under the axon tunnel block_until_ready returns
    # before chained device work finishes (see tools/calibrate_timing.py);
    # fetching the scalar loss is the only trustworthy queue drain
    np.asarray(out[0])

    if trace_dir:
        jax.profiler.start_trace(trace_dir)
    t0 = time.perf_counter()
    for _ in range(steps):
        out = exe.run(main, feed=data, fetch_list=[fetches[0]],
                      return_numpy=False)
    np.asarray(out[0])  # sync point: forces the whole dispatched chain
    dt = time.perf_counter() - t0
    if trace_dir:
        jax.profiler.stop_trace()

    tokens_per_sec = steps * batch * seq_len / dt
    n_params = sum(int(np.prod(p.shape)) for p in main.all_parameters())
    mfu = tokens_per_sec * 6 * n_params / 394e12
    rec = {
        "variant": name,
        "tokens_per_sec": round(tokens_per_sec, 1),
        "ms_per_step": round(1000 * dt / steps, 2),
        "mfu_est": round(mfu, 4),
    }
    print(json.dumps(rec), flush=True)
    scope = fluid.core.scope.global_scope()
    scope.erase(list(scope.var_names()))
    exe.close()
    return rec


def main():
    batch = int(sys.argv[1]) if len(sys.argv) > 1 else 256
    seq = int(sys.argv[2]) if len(sys.argv) > 2 else 128
    trace_dir = os.environ.get("PROFILE_TRACE_DIR")
    only = os.environ.get("PROFILE_ONLY")

    P = max(1, seq // 7) + 1
    variants = [
        # the shipped bench config: flash + gathered head + rbg dropout
        ("bench_config", dict(_max_pred=P, _rng="rbg",
                              use_flash_attention=True,
                              attention_probs_dropout_prob=0.0)),
        # one knob off at a time
        ("no_flash", dict(_max_pred=P, _rng="rbg")),
        ("threefry", dict(_max_pred=P, _rng="threefry",
                          use_flash_attention=True,
                          attention_probs_dropout_prob=0.0)),
        ("full_vocab_head", dict(_rng="rbg", use_flash_attention=True,
                                 attention_probs_dropout_prob=0.0)),
        # the round-2 configuration for the before/after line
        ("r2_baseline", dict(_rng="threefry")),
    ]
    if os.environ.get("PROFILE_EXTRA"):
        variants += [
            ("fp32", dict(_use_amp=False, _max_pred=P, _rng="rbg")),
            ("no_dropout", dict(_max_pred=P, _rng="rbg",
                                hidden_dropout_prob=0.0,
                                attention_probs_dropout_prob=0.0)),
        ]
    for name, kw in variants:
        if only and only != name:
            continue
        use_amp = kw.pop("_use_amp", True)
        max_pred = kw.pop("_max_pred", None)
        rng_impl = kw.pop("_rng", "threefry")
        try:
            run_variant(name, batch, seq, use_amp=use_amp,
                        max_pred=max_pred, rng_impl=rng_impl,
                        trace_dir=trace_dir if name == "bench_config"
                        else None, **kw)
        except Exception as e:  # keep the table going past one bad variant
            print(json.dumps({"variant": name, "error": str(e)[:300]}),
                  flush=True)


if __name__ == "__main__":
    main()
