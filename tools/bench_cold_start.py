#!/usr/bin/env python
"""Cold- vs warm-process startup bench for the persistent compile cache.

Two scenarios, each run in FRESH subprocesses (the cache under test is
cross-process by definition):

- **train**: process start -> first optimized step of a small MLP train
  program. Cold pays trace + XLA compile; warm loads the serialized step
  from ``PADDLE_TPU_CACHE_DIR`` (zero traces).
- **predictor**: Predictor.warmup() over a (batch x seq-like) bucket
  lattice — the serving cold-replica story (ROADMAP item 2's compile
  storm). Cold compiles every lattice point; warm loads each bucket from
  disk in milliseconds.

Each scenario reports cold (cache disabled), populate (cache enabled,
empty — the write-through run), and warm (cache enabled, populated), with
trace/persistent-hit counters from the observability registry so the
"zero compiles" claim is checked, not implied from timing.

``--smoke`` is the tier-1 CI hook (wired by tests/test_compile_cache.py):
asserts warm runs report ZERO traces, nonzero persistent hits, and
bit-identical first-step output vs the cold run.

Usage:
  python tools/bench_cold_start.py [--smoke] [--buckets 1,2,4]
      [--hidden 64] [--cache-dir DIR]
"""

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("OMP_NUM_THREADS", "1")
os.environ.setdefault("OPENBLAS_NUM_THREADS", "1")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


# ---------------------------------------------------------------------------
# child workloads (run in fresh subprocesses)
# ---------------------------------------------------------------------------


def _counters():
    from paddle_tpu.observability import metrics as obs_metrics

    reg = obs_metrics.registry()

    def val(name):
        m = reg.get(name)
        return int(m.value) if m is not None else 0

    return {
        "traces": val("executor_cache_misses_total"),
        "persistent_hits": val("compile_cache_persistent_hits_total"),
    }


def _worker_train(hidden, layers):
    t_start = time.perf_counter()
    import numpy as np

    import paddle_tpu as fluid
    from paddle_tpu.core.ir import program_guard

    main, startup = fluid.Program(), fluid.Program()
    with program_guard(main, startup):
        x = fluid.data("x", shape=[-1, 32])
        y = fluid.data("y", shape=[-1, 1])
        h = x
        for _ in range(layers):
            h = fluid.layers.fc(h, size=hidden, act="relu")
        pred = fluid.layers.fc(h, size=1)
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
        fluid.optimizer.Adam(learning_rate=0.01).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        rng = np.random.RandomState(0)
        feed = {"x": rng.randn(8, 32).astype("float32"),
                "y": rng.randn(8, 1).astype("float32")}
        out = exe.run(main, feed=feed, fetch_list=[loss])
    first_step_s = time.perf_counter() - t_start
    rec = {"startup_to_first_step_s": round(first_step_s, 4),
           "first_loss": repr(float(np.asarray(out[0]).reshape(-1)[0]))}
    rec.update(_counters())
    print(json.dumps(rec))


def _worker_predictor(model_dir, buckets):
    t_start = time.perf_counter()
    from paddle_tpu import inference
    from paddle_tpu.observability import metrics as obs_metrics

    config = inference.Config(model_dir)
    config.disable_tpu()
    config.set_serving_buckets([int(b) for b in buckets.split(",")])
    pred = inference.create_predictor(config)
    t_warm = time.perf_counter()
    compiled = pred.warmup()
    warmup_s = time.perf_counter() - t_warm
    hist = obs_metrics.registry().get("predictor_compile_seconds")
    rec = {
        "startup_to_warm_s": round(time.perf_counter() - t_start, 4),
        "warmup_s": round(warmup_s, 4),
        "buckets_warmed": len(compiled),
        "aot_compiles": hist.count if hist is not None else 0,
        "cache_stats": pred.cache_stats(),
    }
    rec.update(_counters())
    print(json.dumps(rec))


# ---------------------------------------------------------------------------
# parent orchestration
# ---------------------------------------------------------------------------


def _run_child(mode, cache_dir, extra_args):
    env = dict(os.environ)
    env.pop("PADDLE_TPU_CACHE_DIR", None)
    if cache_dir:
        env["PADDLE_TPU_CACHE_DIR"] = cache_dir
    t0 = time.perf_counter()
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--worker", mode]
        + extra_args,
        env=env, capture_output=True, text=True, timeout=600,
    )
    wall = time.perf_counter() - t0
    if proc.returncode != 0:
        raise RuntimeError(
            f"child {mode} failed:\n{proc.stderr.strip()[-2000:]}"
        )
    line = [l for l in proc.stdout.strip().splitlines()
            if l.startswith("{")][-1]
    rec = json.loads(line)
    rec["process_wall_s"] = round(wall, 4)
    return rec


def _make_model(dirname, hidden, layers):
    import numpy as np

    import paddle_tpu as fluid
    from paddle_tpu.core.ir import program_guard

    main, startup = fluid.Program(), fluid.Program()
    with program_guard(main, startup):
        x = fluid.data("x", shape=[-1, 32])
        h = x
        for _ in range(layers):
            h = fluid.layers.fc(h, size=hidden, act="relu")
        pred = fluid.layers.fc(h, size=1)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        fluid.io.save_inference_model(dirname, ["x"], [pred], exe,
                                      main_program=main)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--worker", choices=["train", "predictor"])
    ap.add_argument("--model-dir")
    ap.add_argument("--buckets", default="1,2,4")
    ap.add_argument("--hidden", type=int, default=64)
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--cache-dir", default=None)
    ap.add_argument("--smoke", action="store_true")
    args = ap.parse_args()

    if args.worker == "train":
        return _worker_train(args.hidden, args.layers)
    if args.worker == "predictor":
        return _worker_predictor(args.model_dir, args.buckets)

    cache_dir = args.cache_dir or tempfile.mkdtemp(prefix="ptcc_bench_")
    model_dir = os.path.join(tempfile.mkdtemp(prefix="ptcc_model_"), "model")
    _make_model(model_dir, args.hidden, args.layers)

    report = {"cache_dir": cache_dir}
    train_args = ["--hidden", str(args.hidden),
                  "--layers", str(args.layers)]
    report["train_cold"] = _run_child("train", None, train_args)
    report["train_populate"] = _run_child("train", cache_dir, train_args)
    report["train_warm"] = _run_child("train", cache_dir, train_args)

    pred_args = ["--model-dir", model_dir, "--buckets", args.buckets,
                 "--hidden", str(args.hidden), "--layers", str(args.layers)]
    report["predictor_cold"] = _run_child("predictor", None, pred_args)
    report["predictor_populate"] = _run_child("predictor", cache_dir,
                                              pred_args)
    report["predictor_warm"] = _run_child("predictor", cache_dir, pred_args)

    cold, warm = report["train_cold"], report["train_warm"]
    report["summary"] = {
        "train_first_step_cold_s": cold["startup_to_first_step_s"],
        "train_first_step_warm_s": warm["startup_to_first_step_s"],
        "train_warm_traces": warm["traces"],
        "predictor_warmup_cold_s": report["predictor_cold"]["warmup_s"],
        "predictor_warmup_warm_s": report["predictor_warm"]["warmup_s"],
        "predictor_warm_aot_compiles":
            report["predictor_warm"]["aot_compiles"],
    }
    print(json.dumps(report, indent=1))

    if args.smoke:
        _smoke_asserts(report)
        print("SMOKE OK")


def _smoke_asserts(report):
    warm = report["train_warm"]
    assert warm["traces"] == 0, \
        f"warm train process retraced: {warm['traces']} traces"
    assert warm["persistent_hits"] > 0, "warm train saw no persistent hits"
    # correctness, not just speed: the warm (deserialized) step must
    # produce the bit-identical first loss
    assert warm["first_loss"] == report["train_cold"]["first_loss"], (
        f"warm loss {warm['first_loss']} != cold "
        f"{report['train_cold']['first_loss']}"
    )
    pw = report["predictor_warm"]
    assert pw["aot_compiles"] == 0, \
        f"warm predictor compiled {pw['aot_compiles']} buckets"
    assert pw["cache_stats"]["persistent_hits"] == pw["buckets_warmed"] \
        or pw["persistent_hits"] > 0, "warm predictor saw no persistent hits"


if __name__ == "__main__":
    sys.exit(main())
