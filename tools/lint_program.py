#!/usr/bin/env python
"""Lint serialized programs (train or inference) with the static analyzers.

Subcommands (CI contract: exit 0 = clean, 1 = lint findings, 2 = internal
error; ``--json`` emits one machine-readable report line per program):

  verify       program verifier (use-before-def, dangling vars, dtype/rank
               violations, unknown ops) — the default when no subcommand
               is given, so pre-PR-9 invocations keep working
  shapes       whole-program symbolic shape/dtype inference
               (analysis/shapes.py): shape mismatches + the AMP
               fp32-matmul lint
  sharding     static PartitionSpec propagation (analysis/sharding.py):
               findings are predicted WEIGHT-SIZED collectives — a
               parameter the layout leaves replicated in a tensor-sharded
               program pays a full weight gather per step
  collectives  the same propagation as a byte-budget linter:
               ``--budget-kb N`` fails on any predicted collective moving
               more than N KB per device
  memory       liveness-driven peak-HBM estimate + the donation-safety
               hard errors (read-after-donate, donated-var-fetched,
               donated-var-aliased-twice)
  cost         roofline cost model (analysis/cost.py): predicted step
               seconds / MFU / per-op compute-vs-memory-bound
               classification on ``--machine`` (tpu-v4-8 default), the
               per-axis collective budget, the hierarchical-collective
               (dcn-allreduce) linter when ``--tag AXIS=dcn`` declares a
               slow axis, and ``--budget-step-ms`` /
               ``--budget-collective-kb`` / ``--min-mfu`` gates
  smoke        the fast-tier CI gate: shapes+sharding+donation over every
               examples/ build_programs() graph, plus a drift check of
               STATIC_EVIDENCE_r09.json's static predictions against a
               fresh recompute (the live-HLO half is gated by
               tests/test_hlo.py::test_static_evidence_r09_committed)

Accepts raw ``Program.to_bytes()`` JSON files or saved inference
``__model__`` descs (embedded feed/fetch names ride along), and
``--builtin mnist|mnist_conv|transformer`` for freshly-built models.

Usage:
  python tools/lint_program.py path/to/__model__ [path2 ...]
  python tools/lint_program.py shapes model.json --feed-shape x=32,13
  python tools/lint_program.py sharding --builtin transformer \\
      --mesh 2x4:data,model --spec-layout --json
  python tools/lint_program.py collectives model.json --mesh 2x4:data,model \\
      --budget-kb 192
  python tools/lint_program.py cost --builtin transformer \\
      --mesh 2x4:dcn,data --tag dcn=dcn --machine tpu-v4-8 --json
  python tools/lint_program.py smoke
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

BUILTINS = ("mnist", "mnist_conv", "transformer")
SUBCOMMANDS = ("verify", "shapes", "sharding", "collectives", "memory",
               "cost", "smoke")

EXIT_CLEAN, EXIT_FINDINGS, EXIT_INTERNAL = 0, 1, 2

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

def _discover_examples():
    """Every examples/*.py defining build_programs() — the contract
    examples/README.md documents. Derived from the filesystem (not a
    hand-list) so a new example enters the smoke gates — and the mirrors
    in tests/test_static_analysis.py — without a list to forget."""
    names = []
    for fn in sorted(os.listdir(os.path.join(REPO, "examples"))):
        path = os.path.join(REPO, "examples", fn)
        if fn.endswith(".py"):
            with open(path) as f:
                if "def build_programs" in f.read():
                    names.append(fn[:-3])
    return tuple(names)


EXAMPLES = _discover_examples()


def _ensure_virtual_devices(n):
    """The sharding/collectives subcommands need an n-device mesh; on the
    CPU lint rig that means forcing virtual host devices BEFORE jax
    initializes."""
    flags_env = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags_env:
        os.environ["XLA_FLAGS"] = (
            flags_env + f" --xla_force_host_platform_device_count={n}"
        ).strip()
    os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _mesh_arg_devices(argv):
    """Pre-parse --mesh so the virtual-device env is set before jax loads."""
    for i, a in enumerate(argv):
        spec = None
        if a == "--mesh" and i + 1 < len(argv):
            spec = argv[i + 1]
        elif a.startswith("--mesh="):
            spec = a.split("=", 1)[1]
        if spec:
            try:
                shape = _parse_mesh(spec)[0]
                n = 1
                for d in shape:
                    n *= d
                return max(n, 1)
            except Exception:
                return None
    return None


def _usage_error(msg):
    """Bad invocation — exit EXIT_INTERNAL (2), never EXIT_FINDINGS (1):
    CI gates on 1 meaning 'the program has lint findings', and a malformed
    command line must not read as that."""
    print(msg, file=sys.stderr)
    raise SystemExit(EXIT_INTERNAL)


def _parse_mesh(spec):
    """'2x4:data,model' -> ((2, 4), ('data', 'model'))."""
    shape_s, _, axes_s = spec.partition(":")
    try:
        shape = tuple(int(d) for d in shape_s.lower().split("x"))
    except ValueError:
        shape, axes = (), ()
    else:
        axes = tuple(a for a in axes_s.split(",") if a)
    if not axes or len(axes) != len(shape):
        _usage_error(
            f"bad --mesh '{spec}': want SHAPE:AXES like 2x4:data,model"
        )
    return shape, axes


def _parse_feed_shapes(entries):
    """['x=32,13', 'y=32,1'] -> {'x': (32, 13), 'y': (32, 1)}."""
    out = {}
    for e in entries or []:
        name, _, dims = e.partition("=")
        if not dims:
            _usage_error(f"bad --feed-shape '{e}': want name=2,8")
        out[name] = tuple(int(d) for d in dims.replace("x", ",").split(","))
    return out


def _load_program(path):
    """Load a serialized program; returns (program, feed_names, fetch_names).
    Handles both Program.to_bytes() output and save_inference_model's
    __model__ desc (feed/fetch names embedded)."""
    from paddle_tpu.core.ir import Program

    with open(path, "rb") as f:
        data = f.read()
    desc = json.loads(data.decode("utf-8"))
    program = Program.from_bytes(data)
    return (program, desc.get("feed_var_names", []),
            desc.get("fetch_var_names", []))


def _build_builtin(name):
    """Build a known model's train program in-process (no training, no
    execution) — lints the graph builders themselves."""
    import paddle_tpu as fluid

    if name in ("mnist", "mnist_conv"):
        from paddle_tpu.models import mnist

        main, startup, feeds, fetches = mnist.build_mnist_train(
            use_conv=(name == "mnist_conv")
        )
    elif name == "transformer":
        from paddle_tpu.models import transformer as tfm

        main, startup, feeds, fetches = tfm.build_wmt_train(
            tfm.TransformerConfig.tiny(), src_len=8, tgt_len=8,
            optimizer=fluid.optimizer.Adam(1e-3),
        )
    else:
        _usage_error(f"unknown --builtin '{name}'; have {BUILTINS}")
    feed_names = [f if isinstance(f, str) else f.name for f in feeds]
    fetch_names = [f if isinstance(f, str) else f.name for f in fetches]
    return main, feed_names, fetch_names


def _iter_programs(args, feed, fetch):
    for path in args.programs:
        program, ffeed, ffetch = _load_program(path)
        yield os.path.basename(path), program, ffeed or feed, \
            ffetch or fetch
    for name in getattr(args, "builtin", None) or []:
        program, bfeed, bfetch = _build_builtin(name)
        yield f"builtin:{name}", program, bfeed, bfetch


def _diag_json(d):
    return {
        "severity": d.severity, "code": d.code, "message": d.message,
        "block": d.block_idx, "op_index": d.op_index, "op_type": d.op_type,
        "var": d.var,
    }


def _report(label, pass_name, diags, extra=None, as_json=False,
            warnings_as_errors=False, out=sys.stdout):
    """Shared finding formatter; returns the number of gating findings."""
    errors = [d for d in diags if d.severity == "error"]
    gating = diags if warnings_as_errors else errors
    if as_json:
        payload = {
            "program": label,
            "pass": pass_name,
            "errors": len(errors),
            "warnings": len(diags) - len(errors),
            "diagnostics": [_diag_json(d) for d in diags],
        }
        payload.update(extra or {})
        out.write(json.dumps(payload) + "\n")
    else:
        for d in diags:
            out.write(f"{label}: {d}\n")
        for k, v in (extra or {}).items():
            if k != "events":
                out.write(f"{label}: {k} = {v}\n")
        out.write(
            f"{label}: [{pass_name}] {len(errors)} error(s), "
            f"{len(diags) - len(errors)} warning(s)\n"
        )
    return len(gating)


# ---------------------------------------------------------------------------
# subcommand bodies
# ---------------------------------------------------------------------------


def lint(program, feed_names, fetch_names, label, as_json=False,
         warnings_as_errors=False, out=sys.stdout):
    """Verify one program; returns the number of gating findings.
    (Kept under this name: tests and older CI hooks call it directly.)"""
    from paddle_tpu.analysis.verify import verify_program

    diags = verify_program(
        program, feed_names=feed_names, fetch_names=fetch_names
    )
    errors = [d for d in diags if d.severity == "error"]
    gating = diags if warnings_as_errors else errors
    if as_json:
        out.write(json.dumps({
            "program": label,
            "errors": len(errors),
            "warnings": len(diags) - len(errors),
            "diagnostics": [_diag_json(d) for d in diags],
        }) + "\n")
    else:
        for d in diags:
            out.write(f"{label}: {d}\n")
        out.write(
            f"{label}: {len(errors)} error(s), "
            f"{len(diags) - len(errors)} warning(s)\n"
        )
    return len(gating)


def _cmd_shapes(args):
    from paddle_tpu.analysis.shapes import infer_shapes

    feed_shapes = _parse_feed_shapes(args.feed_shape)
    failures = 0
    for label, program, _feed, _fetch in _iter_programs(args, [], []):
        rep = infer_shapes(program, feed_shapes=feed_shapes)
        failures += _report(
            label, "shapes", rep.diagnostics,
            extra={"unresolved_ops": sorted(rep.unresolved),
                   "amp_mode": rep.amp_mode},
            as_json=args.as_json,
            warnings_as_errors=args.warnings_as_errors,
        )
    return failures


def _make_mesh(args):
    shape, axes = _parse_mesh(args.mesh)
    from paddle_tpu.parallel.env import make_mesh

    return make_mesh(shape=shape, axis_names=axes)


def _sharding_report(args, program, feed_shapes):
    from paddle_tpu.analysis.sharding import analyze_sharding

    layout = None
    if args.spec_layout:
        from paddle_tpu.parallel.spec_layout import SpecLayout

        layout = SpecLayout()
    return analyze_sharding(
        program, _make_mesh(args), spec_layout=layout,
        feed_shapes=feed_shapes,
    )


def _cmd_sharding(args):
    from paddle_tpu.analysis.sharding import (
        weight_param_shapes,
        weight_sized_events,
    )
    from paddle_tpu.analysis.verify import Diagnostic

    feed_shapes = _parse_feed_shapes(args.feed_shape)
    failures = 0
    for label, program, _feed, _fetch in _iter_programs(args, [], []):
        rep = _sharding_report(args, program, feed_shapes)
        diags = list(rep.diagnostics)
        for e in weight_sized_events(rep, weight_param_shapes(program)):
            diags.append(Diagnostic(
                "error", "weight-sized-collective",
                f"predicted {e.kind} of FULL weight '{e.var}' "
                f"({list(e.shape)}, {e.bytes} bytes): {e.cause} — shard "
                f"this parameter (spec_layout registry or an override) "
                f"or every step pays a weight-sized gather",
                op_type=e.op_type, op_index=e.op_index, var=e.var,
            ))
        failures += _report(
            label, "sharding", diags,
            extra={"max_bytes": rep.max_bytes(),
                   "total_bytes": rep.total_bytes(),
                   "by_kind": rep.by_kind(),
                   "events": [e.to_json() for e in rep.events[:64]]},
            as_json=args.as_json,
            warnings_as_errors=args.warnings_as_errors,
        )
    return failures


def _cmd_collectives(args):
    from paddle_tpu.analysis.sharding import collective_budget_diagnostics

    feed_shapes = _parse_feed_shapes(args.feed_shape)
    budget = args.budget_kb * 1024
    failures = 0
    for label, program, _feed, _fetch in _iter_programs(args, [], []):
        rep = _sharding_report(args, program, feed_shapes)
        diags = list(rep.diagnostics)
        diags += collective_budget_diagnostics(rep, budget)
        failures += _report(
            label, "collectives", diags,
            extra={"budget_bytes": budget, "max_bytes": rep.max_bytes(),
                   "by_kind": rep.by_kind(),
                   "events": [e.to_json() for e in rep.events[:64]]},
            as_json=args.as_json,
            warnings_as_errors=args.warnings_as_errors,
        )
    return failures


def _parse_axis_tags(entries):
    """['dcn=dcn', 'data=ici'] -> {'dcn': 'dcn', 'data': 'ici'}."""
    out = {}
    for e in entries or []:
        ax, _, tag = e.partition("=")
        if not ax or tag not in ("ici", "dcn"):
            _usage_error(f"bad --tag '{e}': want AXIS=ici|dcn")
        out[ax] = tag
    return out


def _cmd_cost(args):
    from paddle_tpu.analysis.cost import (
        MACHINES,
        analyze_cost,
        check_cost_budgets,
        hierarchical_collective_diagnostics,
    )

    if args.machine not in MACHINES:
        _usage_error(
            f"unknown --machine '{args.machine}'; have {sorted(MACHINES)}"
        )
    axis_tags = _parse_axis_tags(args.tag)
    feed_shapes = _parse_feed_shapes(args.feed_shape)
    mesh = _make_mesh(args) if args.mesh else None
    layout = None
    if getattr(args, "spec_layout", False):
        from paddle_tpu.parallel.spec_layout import SpecLayout

        layout = SpecLayout()
    batch_axes = tuple(a for a in (args.batch_spec or "").split(",") if a)
    failures = 0
    for label, program, feed, fetch in _iter_programs(args, [], []):
        input_specs = None
        if batch_axes:
            from jax.sharding import PartitionSpec as P

            input_specs = {n: P(batch_axes) for n in feed}
        rep = analyze_cost(
            program, machine=args.machine, mesh=mesh,
            axis_tags=axis_tags or None, spec_layout=layout,
            input_specs=input_specs,
            feed_shapes=feed_shapes, fetch_names=fetch,
        )
        diags = list(rep.diagnostics)
        diags += hierarchical_collective_diagnostics(rep)
        diags += check_cost_budgets(
            rep, step_ms=args.budget_step_ms,
            collective_kb=args.budget_collective_kb, min_mfu=args.min_mfu,
        )
        j = rep.to_json(ops_limit=16)
        # show the 1F1B headroom next to each committed GPipe bubble —
        # the number the pipeline runtime must beat (what-if only; the
        # committed entry stays the program's own schedule). m > s has no
        # contention-free interleaved window, so no what-if there.
        from paddle_tpu.parallel.pipeline_runtime.schedule import (
            predicted_bubble,
        )

        pipeline = []
        for ent in j["pipeline"]:
            ent = dict(ent)
            s, m = ent["stages"], ent["num_microbatches"]
            ent["bubble_1f1b_whatif"] = (
                round(predicted_bubble("1f1b", s, m, 2), 6)
                if s > 1 and m <= s else None
            )
            pipeline.append(ent)
        j["pipeline"] = pipeline
        failures += _report(
            label, "cost", diags,
            extra={"machine": args.machine,
                   "step_seconds": j["step_seconds"],
                   "mfu": j["mfu"],
                   "total_flops": j["total_flops"],
                   "total_hbm_bytes": j["total_hbm_bytes"],
                   "bound_counts": j["bound_counts"],
                   "per_axis": j["per_axis"],
                   "unknown_ops": j["unknown_ops"],
                   "pipeline": j["pipeline"],
                   "events": j["collectives"]},
            as_json=args.as_json,
            warnings_as_errors=args.warnings_as_errors,
        )
    return failures


def _static_donation_plan(program, feed_names, fetch_names):
    """plan_step's donation classification without a scope: persistable
    vars written by live ops and not fetched are donated, the rest of the
    persistable reads are read-only."""
    block = program.global_block()
    from paddle_tpu.analysis.usedef import UseDefMap

    usedef = UseDefMap(block)
    read, written = set(), set()
    for op in block.ops:
        read |= usedef.reads_of(op)
        written |= usedef.writes_of(op)

    def persistable(n):
        v = block._find_var_recursive(n)
        return v is not None and v.persistable

    fetches = set(fetch_names)
    donated = sorted(n for n in written
                     if persistable(n) and n not in fetches)
    readonly = sorted(n for n in read
                      if persistable(n) and n not in set(donated))
    return donated, readonly


def _cmd_memory(args):
    from paddle_tpu.analysis.memory import (
        check_donation_safety,
        estimate_peak_hbm,
    )

    feed_shapes = _parse_feed_shapes(args.feed_shape)
    failures = 0
    for label, program, feed, fetch in _iter_programs(args, [], []):
        donated, readonly = _static_donation_plan(program, feed, fetch)
        diags = check_donation_safety(program, donated, readonly, fetch)
        donate = not args.no_donate
        rep = estimate_peak_hbm(
            program, feed_shapes=feed_shapes, fetch_names=fetch,
            donate=donate,
        )
        diags = diags + rep.diagnostics
        failures += _report(
            label, "memory", diags,
            extra={"peak": rep.to_json(), "donated": len(donated)},
            as_json=args.as_json,
            warnings_as_errors=args.warnings_as_errors,
        )
    return failures


def _build_example(name):
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        f"lint_example_{name}", os.path.join(REPO, "examples", f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    built = mod.build_programs()
    main, startup, feed_names = built[0], built[1], built[2]
    fetch_names = [f if isinstance(f, str) else f.name for f in built[3]]
    return main, startup, feed_names, fetch_names


def _cmd_smoke(args):
    """Fast-tier CI gate: every examples/ program is clean under shapes +
    sharding (8-way dp mesh) + donation safety, and the committed
    STATIC_EVIDENCE_r09.json static predictions match a fresh recompute
    (drift here means the analyzer or the layout changed without
    regenerating evidence — run tools/static_report.py)."""
    import builtins

    as_json = bool(getattr(args, "as_json", False))
    findings = []

    def print(*a, **kw):  # noqa: A001 - JSON mode keeps stdout machine-only
        msg = " ".join(str(x) for x in a)
        if msg.startswith("SMOKE FAIL"):
            findings.append(msg)
        kw.setdefault("file", sys.stderr if as_json else sys.stdout)
        builtins.print(*a, **kw)

    from paddle_tpu.analysis.memory import check_donation_safety
    from paddle_tpu.analysis.shapes import infer_shapes
    from paddle_tpu.analysis.sharding import analyze_sharding
    from paddle_tpu.parallel.env import make_mesh
    from paddle_tpu.passes import (
        apply_deferred_sharded_embedding_rewrite,
        apply_deferred_sparse_rewrite,
    )

    failures = 0
    mesh = make_mesh(shape=(8,), axis_names=("data",))
    for name in EXAMPLES:
        main, startup, feed_names, fetch_names = _build_example(name)
        apply_deferred_sparse_rewrite(main)
        apply_deferred_sharded_embedding_rewrite(main)
        before = failures
        for tag, program in ((f"{name}:main", main),
                             (f"{name}:startup", startup)):
            rep = infer_shapes(program)
            errs = rep.errors()
            if errs:
                failures += 1
                print(f"SMOKE FAIL {tag}: shape errors: "
                      f"{[str(d)[:120] for d in errs[:3]]}")
        srep = analyze_sharding(main, mesh)
        # weight-sized linting needs a tensor-sharded placement, which no
        # example uses — that class is covered by the evidence drift gate
        # below (registry + megatron-control arms). What IS checkable on
        # this pure-dp mesh is the grad-sync law: events only for
        # trainable parameters, never optimizer slots/scheduler counters
        # (a phantom event here inflates every downstream byte budget)
        trainable = {p.name for p in main.all_parameters()}
        phantom = sorted({e.var for e in srep.events
                          if e.cause == "grad-sync"} - trainable)
        if phantom:
            failures += 1
            print(f"SMOKE FAIL {name}: grad-sync predicted for "
                  f"non-parameter state: {phantom[:3]}")
        donated, readonly = _static_donation_plan(
            main, feed_names, fetch_names
        )
        ddiags = check_donation_safety(main, donated, readonly,
                                       fetch_names)
        if ddiags:
            failures += 1
            print(f"SMOKE FAIL {name}: donation safety: "
                  f"{[d.code for d in ddiags[:3]]}")
        if failures == before:
            print(f"smoke: {name} clean "
                  f"(donated={len(donated)}, events={len(srep.events)})")

    # static-evidence drift gate: recompute the static half of
    # STATIC_EVIDENCE_r09.json and compare
    path = os.path.join(REPO, "STATIC_EVIDENCE_r09.json")
    if not os.path.exists(path):
        print("SMOKE FAIL: STATIC_EVIDENCE_r09.json missing "
              "(run tools/static_report.py --out STATIC_EVIDENCE_r09.json)")
        return failures + 1
    with open(path) as f:
        committed = json.load(f)
    import importlib.util

    sr_spec = importlib.util.spec_from_file_location(
        "static_report", os.path.join(REPO, "tools", "static_report.py")
    )
    static_report = importlib.util.module_from_spec(sr_spec)
    sr_spec.loader.exec_module(static_report)

    fresh = static_report.static_sections()
    for arm, sec in fresh.items():
        # a fresh arm absent from the committed file IS drift (exit 1),
        # not a KeyError traceback (exit 2)
        want = committed.get("arms", {}).get(arm, {}).get("static", {})
        for key in ("weight_sized_count", "max_bytes", "budget_verdict",
                    "weight_sized_shapes"):
            if want.get(key) != sec.get(key):
                failures += 1
                print(f"SMOKE FAIL: static evidence drift in {arm}.{key}: "
                      f"committed {want.get(key)} != fresh {sec.get(key)}")
    for arm in sorted(set(committed.get("arms", {})) - set(fresh)):
        # committed claims nothing re-derives any more are drift too: an
        # arm deleted/renamed in static_report.py must regenerate the file
        failures += 1
        print(f"SMOKE FAIL: committed evidence arm '{arm}' is no longer "
              f"derived by tools/static_report.py — regenerate "
              f"STATIC_EVIDENCE_r09.json or restore the arm")
    if not failures:
        print("smoke: all examples clean, static evidence matches")
    if as_json:
        builtins.print(json.dumps({
            "program": "smoke", "pass": not failures,
            "examples": list(EXAMPLES), "failures": findings,
        }))
    return failures


# ---------------------------------------------------------------------------
# CLI plumbing
# ---------------------------------------------------------------------------


def _add_common(ap, with_mesh=False, mesh_required=True):
    ap.add_argument("programs", nargs="*", help="serialized program files")
    ap.add_argument("--builtin", action="append", default=[],
                    choices=BUILTINS,
                    help="lint a freshly-built known model program")
    ap.add_argument("--feed-shape", action="append", default=[],
                    metavar="NAME=D0,D1",
                    help="bind a feed's symbolic dims (repeatable)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="one JSON report line per program")
    ap.add_argument("--warnings-as-errors", action="store_true")
    if with_mesh:
        ap.add_argument("--mesh", required=mesh_required,
                        default=None, metavar="SHAPE:AXES",
                        help="virtual mesh, e.g. 2x4:data,model"
                        + ("" if mesh_required
                           else " (omit for single-device)"))
        ap.add_argument("--spec-layout", action="store_true",
                        help="place parameters through the canonical "
                        "SpecLayout registry (parallel/spec_layout.py)")


def main(argv=None):
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] in ("-h", "--help"):
        # top-level help must describe the SUBCOMMAND surface, not fall
        # through to the legacy verify parser (which knows nothing of the
        # other passes — the help/usage drift fixed in round 16)
        print(__doc__)
        return EXIT_CLEAN
    sub = argv[0] if argv and argv[0] in SUBCOMMANDS else None
    if sub in ("sharding", "collectives", "cost"):
        n = _mesh_arg_devices(argv)
        if n:
            _ensure_virtual_devices(n)
    if sub == "smoke":
        _ensure_virtual_devices(8)

    try:
        if sub is None:
            return _legacy_main(argv)
        body = argv[1:]
        if sub == "verify":
            return _legacy_main(body)
        ap = argparse.ArgumentParser(
            prog=f"lint_program.py {sub}",
            description=f"static '{sub}' lint over serialized programs",
        )
        if sub == "smoke":
            ap.add_argument("--json", action="store_true", dest="as_json",
                            help="one JSON summary line on stdout "
                            "(progress goes to stderr)")
            return (EXIT_FINDINGS if _cmd_smoke(ap.parse_args(body))
                    else EXIT_CLEAN)
        _add_common(ap, with_mesh=sub in ("sharding", "collectives", "cost"),
                    mesh_required=sub != "cost")
        if sub == "collectives":
            ap.add_argument("--budget-kb", type=int, required=True,
                            help="per-collective byte budget in KB")
        if sub == "memory":
            ap.add_argument("--no-donate", action="store_true",
                            help="estimate without buffer donation")
        if sub == "cost":
            ap.add_argument("--machine", default="tpu-v4-8",
                            metavar="NAME",
                            help="machine model (analysis/cost.py "
                            "MACHINES); unknown names exit 2")
            ap.add_argument("--tag", action="append", default=[],
                            metavar="AXIS=ici|dcn",
                            help="tag a mesh axis's link tier "
                            "(repeatable); a 'dcn' tag arms the "
                            "hierarchical-allreduce linter")
            ap.add_argument("--budget-step-ms", type=float, default=0.0,
                            help="fail if predicted step time exceeds "
                            "this many ms (0 disables)")
            ap.add_argument("--budget-collective-kb", type=int, default=0,
                            help="fail if any mesh axis carries more "
                            "on-wire KB per step (0 disables)")
            ap.add_argument("--min-mfu", type=float, default=0.0,
                            help="fail if predicted MFU is below this "
                            "floor (0 disables)")
            ap.add_argument("--batch-spec", default="",
                            metavar="AXIS[,AXIS]",
                            help="shard every feed's batch dim over "
                            "these mesh axes (naive dp over dcn,ici — "
                            "the layout the hierarchical linter flags)")
        args = ap.parse_args(body)
        if not args.programs and not args.builtin:
            ap.error("nothing to lint: pass program files and/or --builtin")
        body_fn = {
            "shapes": _cmd_shapes,
            "sharding": _cmd_sharding,
            "collectives": _cmd_collectives,
            "memory": _cmd_memory,
            "cost": _cmd_cost,
        }[sub]
        return EXIT_FINDINGS if body_fn(args) else EXIT_CLEAN
    except SystemExit:
        raise
    except Exception:
        import traceback

        traceback.print_exc()
        return EXIT_INTERNAL


def _legacy_main(argv):
    ap = argparse.ArgumentParser(
        description="Lint serialized programs with the IR verifier"
    )
    ap.add_argument("programs", nargs="*", help="serialized program files")
    ap.add_argument("--builtin", action="append", default=[],
                    choices=BUILTINS,
                    help="lint a freshly-built known model program")
    ap.add_argument("--feed", default="",
                    help="comma-separated feed names (files without "
                    "embedded feed names)")
    ap.add_argument("--fetch", default="",
                    help="comma-separated fetch names")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="one JSON report line per program")
    ap.add_argument("--warnings-as-errors", action="store_true")
    args = ap.parse_args(argv)
    if not args.programs and not args.builtin:
        ap.error("nothing to lint: pass program files and/or --builtin")

    feed = [n for n in args.feed.split(",") if n]
    fetch = [n for n in args.fetch.split(",") if n]

    failures = 0
    for path in args.programs:
        program, ffeed, ffetch = _load_program(path)
        failures += lint(
            program, ffeed or feed, ffetch or fetch, os.path.basename(path),
            as_json=args.as_json, warnings_as_errors=args.warnings_as_errors,
        )
    for name in args.builtin:
        program, bfeed, bfetch = _build_builtin(name)
        failures += lint(
            program, bfeed, bfetch, f"builtin:{name}",
            as_json=args.as_json, warnings_as_errors=args.warnings_as_errors,
        )
    return EXIT_FINDINGS if failures else EXIT_CLEAN


if __name__ == "__main__":
    sys.exit(main())
