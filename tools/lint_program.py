#!/usr/bin/env python
"""Lint a serialized program (train or inference) with the program verifier.

Runs analysis/verify.py over a program file and exits nonzero when errors
are found — the CI hook that keeps every serialized/example program
well-formed (use-before-def, dangling vars, dtype/rank violations, orphaned
sub-blocks) on every PR.

Accepts either a raw ``Program.to_bytes()`` JSON file or a saved inference
``__model__`` (whose desc embeds feed/fetch names — they are used as the
lint's feed/fetch context automatically). ``--builtin`` lints a
freshly-built model program instead of a file.

Usage:
  python tools/lint_program.py path/to/__model__ [path2 ...]
  python tools/lint_program.py --builtin mnist --builtin transformer
  python tools/lint_program.py model.json --feed x,y --fetch loss \\
      [--json] [--warnings-as-errors]
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

BUILTINS = ("mnist", "mnist_conv", "transformer")


def _load_program(path):
    """Load a serialized program; returns (program, feed_names, fetch_names).
    Handles both Program.to_bytes() output and save_inference_model's
    __model__ desc (feed/fetch names embedded)."""
    from paddle_tpu.core.ir import Program

    with open(path, "rb") as f:
        data = f.read()
    # from_bytes only reads format_version/random_seed/blocks, so the
    # embedded feed/fetch keys of a saved __model__ can ride along
    desc = json.loads(data.decode("utf-8"))
    program = Program.from_bytes(data)
    return (program, desc.get("feed_var_names", []),
            desc.get("fetch_var_names", []))


def _build_builtin(name):
    """Build a known model's train program in-process (no training, no
    execution) — lints the graph builders themselves."""
    import paddle_tpu as fluid

    if name in ("mnist", "mnist_conv"):
        from paddle_tpu.models import mnist

        main, startup, feeds, fetches = mnist.build_mnist_train(
            use_conv=(name == "mnist_conv")
        )
    elif name == "transformer":
        from paddle_tpu.models import transformer as tfm

        main, startup, feeds, fetches = tfm.build_wmt_train(
            tfm.TransformerConfig.tiny(), src_len=8, tgt_len=8,
            optimizer=fluid.optimizer.Adam(1e-3),
        )
    else:
        raise SystemExit(f"unknown --builtin '{name}'; have {BUILTINS}")
    feed_names = [f if isinstance(f, str) else f.name for f in feeds]
    fetch_names = [f if isinstance(f, str) else f.name for f in fetches]
    return main, feed_names, fetch_names


def lint(program, feed_names, fetch_names, label, as_json=False,
         warnings_as_errors=False, out=sys.stdout):
    """Verify one program; returns the number of gating findings."""
    from paddle_tpu.analysis.verify import verify_program

    diags = verify_program(
        program, feed_names=feed_names, fetch_names=fetch_names
    )
    errors = [d for d in diags if d.severity == "error"]
    gating = diags if warnings_as_errors else errors
    if as_json:
        out.write(json.dumps({
            "program": label,
            "errors": len(errors),
            "warnings": len(diags) - len(errors),
            "diagnostics": [
                {
                    "severity": d.severity,
                    "code": d.code,
                    "message": d.message,
                    "block": d.block_idx,
                    "op_index": d.op_index,
                    "op_type": d.op_type,
                    "var": d.var,
                }
                for d in diags
            ],
        }) + "\n")
    else:
        for d in diags:
            out.write(f"{label}: {d}\n")
        out.write(
            f"{label}: {len(errors)} error(s), "
            f"{len(diags) - len(errors)} warning(s)\n"
        )
    return len(gating)


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="Lint serialized programs with the IR verifier"
    )
    ap.add_argument("programs", nargs="*", help="serialized program files")
    ap.add_argument("--builtin", action="append", default=[],
                    choices=BUILTINS,
                    help="lint a freshly-built known model program")
    ap.add_argument("--feed", default="",
                    help="comma-separated feed names (files without "
                    "embedded feed names)")
    ap.add_argument("--fetch", default="",
                    help="comma-separated fetch names")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="one JSON report line per program")
    ap.add_argument("--warnings-as-errors", action="store_true")
    args = ap.parse_args(argv)
    if not args.programs and not args.builtin:
        ap.error("nothing to lint: pass program files and/or --builtin")

    feed = [n for n in args.feed.split(",") if n]
    fetch = [n for n in args.fetch.split(",") if n]

    failures = 0
    for path in args.programs:
        program, ffeed, ffetch = _load_program(path)
        failures += lint(
            program, ffeed or feed, ffetch or fetch, os.path.basename(path),
            as_json=args.as_json, warnings_as_errors=args.warnings_as_errors,
        )
    for name in args.builtin:
        program, bfeed, bfetch = _build_builtin(name)
        failures += lint(
            program, bfeed, bfetch, f"builtin:{name}",
            as_json=args.as_json, warnings_as_errors=args.warnings_as_errors,
        )
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
