#!/usr/bin/env python
"""Sharded vs legacy full-gather checkpoint bench + N->M reshard proof.

Trains a few steps of an fc stack on a dp x tp mesh with parameter
placement from the canonical SpecLayout registry (so weights are real
mesh-sharded jax.Arrays), then measures:

  * legacy save — every persistable np.asarray'd (the full host gather
    the pre-PR-7 AutoCheckpoint always paid) then written as format 1;
  * sharded save — per-shard device->host snapshots into format 2
    (incubate/checkpoint.py), no gather;
  * shard-wise load — load_checkpoint(shardings=...) restoring onto a
    DIFFERENT mesh factorization (N -> M shards) via per-shard
    device_put, asserting the restored parameters are BIT-IDENTICAL to
    the pre-save reference.

`--smoke` runs the seconds-scale shape and asserts the correctness
properties (bit-identical N->M round trip, format-2 manifest, corrupt
shard walks back) — wired into the fast test tier by
tests/test_spec_layout.py. Timing numbers are reported, not asserted:
on the CPU rig a "gather" is a local copy, so the wall-clock delta is
not hardware signal (BASELINE.md bench policy); the structural
properties are.

Usage:
  python tools/bench_checkpoint.py [--hidden 512] [--layers 4]
      [--steps 2] [--smoke] [--json]
"""

import argparse
import json
import os
import shutil
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import numpy as np  # noqa: E402

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def build_model(hidden, layers):
    import paddle_tpu as fluid

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.data("x", shape=[-1, hidden])
        y = fluid.data("y", shape=[-1, 1])
        h = x
        for _ in range(layers):
            h = fluid.layers.fc(h, size=hidden, act="relu")
        pred = fluid.layers.fc(h, size=1)
        loss = fluid.layers.mean(fluid.layers.square_error_cost(pred, y))
        fluid.optimizer.Adam(learning_rate=1e-3).minimize(loss)
    return main, startup, loss


def run(args):
    import paddle_tpu as fluid
    from paddle_tpu.incubate import checkpoint as ck
    from paddle_tpu.parallel.env import make_mesh
    from paddle_tpu.parallel.spec_layout import SpecLayout

    layout = SpecLayout()
    mesh_save = make_mesh(shape=(2, 4), axis_names=("data", "model"))
    mesh_load = make_mesh(shape=(4, 2), axis_names=("data", "model"))
    main, startup, loss = build_model(args.hidden, args.layers)

    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    results = {"hidden": args.hidden, "layers": args.layers}
    with fluid.scope_guard(scope):
        exe.run(startup)
        prog = fluid.CompiledProgram(main).with_parallel(
            mesh=mesh_save, loss_name=loss.name, spec_layout=layout
        )
        rng = np.random.RandomState(0)
        feed = {
            "x": rng.randn(8, args.hidden).astype("float32"),
            "y": rng.randn(8, 1).astype("float32"),
        }
        for _ in range(args.steps):
            exe.run(prog, feed=feed, fetch_list=[loss])

        persistables = [
            v.name for v in main.global_block().vars.values()
            if v.persistable
        ]
        sharded_names = [
            n for n in persistables
            if isinstance(ck.snapshot_value(scope.find_var(n)),
                          ck._ShardSnap)
        ]
        results["persistables"] = len(persistables)
        results["sharded_values"] = len(sharded_names)
        assert sharded_names, "no sharded values — the bench proves nothing"

        # bit-exact reference (one deliberate gather, outside the timers)
        reference = {
            n: np.array(np.asarray(scope.find_var(n)))
            for n in persistables
        }
        results["total_bytes"] = int(
            sum(a.nbytes for a in reference.values())
        )

        # -- legacy full-gather save (format 1) -------------------------
        legacy_dir = tempfile.mkdtemp(prefix="ck_legacy_")
        gather_scope = fluid.Scope()
        t0 = time.perf_counter()
        for n in persistables:
            gather_scope.set(n, np.asarray(scope.find_var(n)))
        ck.AutoCheckpoint(
            exe, main, legacy_dir, save_interval_steps=1, scope=gather_scope
        ).save(0, blocking=True)
        results["save_legacy_gather_s"] = time.perf_counter() - t0

        # -- sharded save (format 2, no gather) -------------------------
        sharded_dir = tempfile.mkdtemp(prefix="ck_sharded_")
        ckpt = ck.AutoCheckpoint(
            exe, main, sharded_dir, save_interval_steps=1, scope=scope
        )
        t0 = time.perf_counter()
        ckpt.save(0, blocking=True)
        results["save_sharded_s"] = time.perf_counter() - t0
        with open(os.path.join(sharded_dir, "ckpt_0",
                               "manifest.json")) as f:
            manifest = json.load(f)
        assert manifest["format"] == 2, manifest["format"]
        assert set(manifest["sharded"]) == set(sharded_names)
        results["manifest_format"] = manifest["format"]
        results["shard_entries"] = sum(
            len(v["shards"]) for v in manifest["sharded"].values()
        )

        # -- N->M shard-wise restore, bit-identity ----------------------
        target = layout.derive_shardings(
            main, persistables,
            [reference[n].shape for n in persistables], mesh_load,
        )
        restore_scope = fluid.Scope()
        t0 = time.perf_counter()
        step = ck.load_checkpoint(
            sharded_dir, scope=restore_scope, shardings=target
        )
        results["load_shardwise_s"] = time.perf_counter() - t0
        assert step == 1, step
        mismatch = [
            n for n in persistables
            if not np.array_equal(
                np.asarray(restore_scope.find_var(n)), reference[n]
            )
        ]
        assert not mismatch, f"N->M round trip not bit-identical: {mismatch}"
        resharded = [
            n for n in sharded_names
            if isinstance(restore_scope.find_var(n), jax.Array)
            and restore_scope.find_var(n).sharding == target[n]
        ]
        assert resharded == sharded_names, (
            "restored values not on the target sharding"
        )
        results["n_to_m_bit_identical"] = True

        # -- corrupt one shard: the chain walks back --------------------
        ckpt.save(1, blocking=True)
        shard_f = os.path.join(sharded_dir, "ckpt_1", "shards_p0.npz")
        raw = bytearray(open(shard_f, "rb").read())
        raw[len(raw) // 2] ^= 0xFF
        with open(shard_f, "wb") as f:
            f.write(bytes(raw))
        walk_scope = fluid.Scope()
        step = ck.load_checkpoint(sharded_dir, scope=walk_scope,
                                  shardings=target)
        assert step == 1, f"corrupt shard did not walk back (step {step})"
        assert os.path.exists(
            os.path.join(sharded_dir, "ckpt_1.corrupt")
        ), "corrupt entry not quarantined"
        assert np.array_equal(
            np.asarray(walk_scope.find_var(sharded_names[0])),
            reference[sharded_names[0]],
        )
        results["corrupt_shard_walks_back"] = True

        shutil.rmtree(legacy_dir, ignore_errors=True)
        shutil.rmtree(sharded_dir, ignore_errors=True)
    return results


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--hidden", type=int, default=512)
    p.add_argument("--layers", type=int, default=4)
    p.add_argument("--steps", type=int, default=2)
    p.add_argument("--smoke", action="store_true",
                   help="tiny shapes + hard asserts (fast-tier CI hook)")
    p.add_argument("--json", action="store_true")
    args = p.parse_args()
    if args.smoke:
        args.hidden, args.layers, args.steps = 64, 2, 1
    results = run(args)
    print(json.dumps(results, indent=1))
    if args.smoke:
        assert results["n_to_m_bit_identical"]
        assert results["corrupt_shard_walks_back"]
        assert results["manifest_format"] == 2
        print("SMOKE OK")


if __name__ == "__main__":
    main()
