#!/usr/bin/env python
"""Serving load generator: closed/open loop over ServingEngine, and
open-loop continuous-batching decode over GenerationEngine.

Closed loop (`--mode closed`): N concurrent clients, each submitting its
next request the moment the previous one returns — measures saturated
throughput and the batcher's coalescing gain. Open loop (`--mode open`):
Poisson arrivals at `--rate` req/s regardless of completions — measures
SLO behavior under offered load, including explicit backpressure
(rejections counted, not retried). Both report one JSON line:
throughput, p50/p99 queue+total latency, mean batch occupancy,
rejection/deadline counters, and the post-warmup compile-cache hit rate
(anything < 1.0 means the bucket lattice is mis-sized for the traffic).

Decode (`--decode`): open-loop autoregressive generation through the
continuous-batching engine (serving/decode) — Poisson arrivals of
mixed-length prompts from weighted tenants, optionally swept over
`--rates`. Reports slot occupancy, tokens/step, tokens/s, per-tenant
token counts and completion ranks, and the occupancy gain over a
request-at-a-time baseline (the PR-2 bucketing discipline: the same
completed requests grouped into admission-order batches of S, each
holding every slot for max(tokens) iterations — what the engine would
have done without iteration-level retirement).

Generation modes (r17): `--sample` replays a committed-threefry sampled
workload through TWO shuffled admission orders and bit-compares both
against the offline reference; `--beam` runs width-3 COW beam search and
bit-compares every ranked hypothesis against the offline beam reference
while asserting block-pool conservation across fork/prune.

`--smoke` runs a seconds-scale configuration and asserts the invariants
(all served, zero retrace after warmup; for --decode also continuous-
vs-offline bit-identity, occupancy gain > 1.5x, and the KERNEL parity
leg: the same paged+chunked+speculative workload under
PADDLE_TPU_KERNELS=off vs =interpret must produce byte-identical
tokens; for --sample/--beam also replay bit-identity, zero retraces
after warmup, and beam block-conservation) — wired into tier-1 CI by
tests/test_serving.py and tests/test_decode.py.

Usage:
  python tools/bench_serving.py [--mode closed|open] [--requests 512]
      [--clients 8] [--rate 200] [--replicas 2] [--max-batch 8]
      [--seq 0] [--deadline-ms 0] [--smoke]
  python tools/bench_serving.py --decode [--requests 128] [--slots 8]
      [--max-len 64] [--rates 50,200,800] [--paged] [--spec]
      [--sample] [--beam] [--smoke]
"""

import argparse
import json
import os
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def _save_model(tmpdir, feat=8, seq=0):
    """Tiny fc stack; with --seq a per-token head over a [-1, -1, feat]
    input (the padded-axis path)."""
    import paddle_tpu as fluid
    from paddle_tpu.core.ir import Program, program_guard

    main, startup = Program(), Program()
    with program_guard(main, startup):
        if seq:
            x = fluid.data("x", [-1, -1, feat])
            h = fluid.layers.fc(x, 16, act="relu", num_flatten_dims=2)
            pred = fluid.layers.fc(h, 4, num_flatten_dims=2)
        else:
            x = fluid.data("x", [-1, feat])
            h = fluid.layers.fc(x, 16, act="relu")
            pred = fluid.layers.fc(h, 4)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        model_dir = os.path.join(tmpdir, "model")
        fluid.io.save_inference_model(model_dir, ["x"], [pred], exe,
                                      main_program=main)
    return model_dir


def _make_request(rng, args):
    rows = int(rng.randint(1, 3))
    if args.seq:
        ln = int(rng.randint(2, args.seq + 1))
        return {"x": rng.randn(rows, ln, args.feat).astype("float32")}
    return {"x": rng.randn(rows, args.feat).astype("float32")}


def run_closed(engine, args, rng):
    from paddle_tpu.serving import ServingError

    lock = threading.Lock()
    served, errors = [], []
    per_client = args.requests // args.clients

    def client(cid):
        crng = np.random.RandomState(1000 + cid)
        for i in range(per_client):
            try:
                resp = engine.submit(
                    _make_request(crng, args), priority=i % 3,
                    deadline_ms=args.deadline_ms or None,
                )
                out = resp.result(timeout=120)
                with lock:
                    served.append(out)
            except ServingError as e:
                with lock:
                    errors.append(e.code)

    threads = [threading.Thread(target=client, args=(c,))
               for c in range(args.clients)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return len(served), errors, time.perf_counter() - t0


def run_open(engine, args, rng):
    from paddle_tpu.serving import ServingError

    responses, errors = [], []
    t0 = time.perf_counter()
    for i in range(args.requests):
        time.sleep(float(rng.exponential(1.0 / args.rate)))
        try:
            responses.append(engine.submit(
                _make_request(rng, args), priority=i % 3,
                deadline_ms=args.deadline_ms or None,
            ))
        except ServingError as e:
            errors.append(e.code)
    served = 0
    for r in responses:
        try:
            r.result(timeout=120)
            served += 1
        except ServingError as e:
            errors.append(e.code)
    return served, errors, time.perf_counter() - t0


# ---------------------------------------------------------------------------
# continuous-batching decode (--decode)
# ---------------------------------------------------------------------------

TENANT_WEIGHTS = {"gold": 2.0, "silver": 1.0}


def _decode_workload(rng, n, max_len, vocab):
    """Alternating short/long requests (the shape where request-at-a-time
    bucketing wastes the most slot-steps: every short request waits for
    the long batchmate to drain)."""
    reqs = []
    tenants = sorted(TENANT_WEIGHTS)
    for i in range(n):
        plen = int(rng.randint(1, 5))
        prompt = [int(t) for t in rng.randint(0, vocab, size=plen)]
        room = max_len - plen
        if i % 2:
            max_new = int(rng.randint(max(room - 4, 1), room + 1))
        else:
            max_new = int(rng.randint(1, 4))
        reqs.append((prompt, max_new,
                     tenants[int(rng.randint(len(tenants)))]))
    return reqs


def _baseline_occupancy(token_counts, slots):
    """Request-at-a-time occupancy on the SAME completed requests: batches
    of S in admission order, each running max(tokens) iterations with no
    mid-flight retirement or admission."""
    total = wasted_steps = 0
    for i in range(0, len(token_counts), slots):
        group = token_counts[i:i + slots]
        total += sum(group)
        wasted_steps += slots * max(group)
    return total / float(max(wasted_steps, 1))


def _jit_count():
    from paddle_tpu.observability import metrics as obs_metrics

    m = obs_metrics.registry().get("lowering_jit_total")
    return int(m.value) if m is not None else 0


def run_decode(args, rng):
    from paddle_tpu.serving.decode import GenerationEngine, build_decoder_model

    engine = GenerationEngine(queue_depth=args.queue_depth,
                              breaker_threshold=0)
    for tenant, weight in TENANT_WEIGHTS.items():
        engine.set_tenant(tenant, weight=weight)
    t0 = time.perf_counter()
    entry = engine.register_model(lambda: build_decoder_model(
        vocab_size=args.vocab, hidden=args.hidden, num_layers=args.layers,
        slots=args.slots, max_len=args.max_len, name="bench", version="1",
    ))
    engine.start()
    # warmup: one request per slot, drained — steady-state executables
    for r in [engine.submit([1, 2], max_new_tokens=2)
              for _ in range(args.slots)]:
        r.result(timeout=120)
    warm_s = time.perf_counter() - t0
    jits_warm = _jit_count()

    m = entry.metrics
    sweep = []
    mismatches = errors = served = verified = 0
    sample = None if args.smoke else args.verify  # None = every request
    for rate in args.rates:
        reqs = _decode_workload(rng, args.requests, args.max_len, args.vocab)
        steps0 = m.count("decode_steps")
        active0 = m.count("active_slot_steps")
        tokens0 = m.count("generated_tokens")
        t0 = time.perf_counter()
        resps = []
        for prompt, max_new, tenant in reqs:
            time.sleep(float(rng.exponential(1.0 / rate)))
            try:
                resps.append(engine.submit(prompt, max_new_tokens=max_new,
                                           tenant=tenant))
            except Exception:
                # open-loop overload IS the measured regime: a rejected
                # submit (queue full / quota) is an error datum, not a
                # bench crash
                resps.append(None)
        outs = []
        for r in resps:
            if r is None:
                outs.append(None)
                errors += 1
                continue
            try:
                outs.append([int(t) for t in r.result(timeout=300)["tokens"]])
                served += 1
            except Exception:
                outs.append(None)
                errors += 1
        wall = time.perf_counter() - t0
        counts = [len(o) for o in outs if o is not None]
        steps = m.count("decode_steps") - steps0
        occupancy = ((m.count("active_slot_steps") - active0)
                     / float(max(steps, 1) * args.slots))
        baseline = _baseline_occupancy(counts, args.slots)
        # bit-identity vs the offline whole-sequence reference (every
        # request under --smoke; a sample otherwise — offline replays the
        # full prefill per token, so it dominates the bench runtime)
        for (prompt, max_new, _t), out in list(zip(reqs, outs))[:sample]:
            if out is None:
                continue
            verified += 1
            if out != entry.offline_decode(prompt, max_new):
                mismatches += 1
        sweep.append({
            "rate_req_per_s": rate,
            "occupancy": round(occupancy, 3),
            "baseline_occupancy": round(baseline, 3),
            "occupancy_gain": round(occupancy / max(baseline, 1e-9), 2),
            "tokens_per_step": round(
                (m.count("generated_tokens") - tokens0) / max(steps, 1), 2),
            "tokens_per_sec": round(sum(counts) / max(wall, 1e-9), 1),
            "decode_steps": steps,
        })

    # fairness burst: equal offered load per tenant under full contention;
    # the weight-2 tenant's requests should finish earlier (smaller mean
    # completion rank), tokens split tracking the 2:1 stride shares
    burst = []
    for i in range(args.slots * 4):
        tenant = sorted(TENANT_WEIGHTS)[i % 2]
        try:
            burst.append((tenant, engine.submit(
                [int(x) for x in rng.randint(0, args.vocab, size=2)],
                max_new_tokens=6, tenant=tenant)))
        except Exception:
            errors += 1
    done = []
    for tenant, resp in burst:
        try:
            resp.result(timeout=300)
            done.append((tenant, resp))
        except Exception:
            errors += 1
    ranks = {}
    for rank, (tenant, _r) in enumerate(
            sorted(done, key=lambda x: x[1].finish_time)):
        ranks.setdefault(tenant, []).append(rank)
    mean_rank = {t: round(sum(r) / len(r), 2) for t, r in ranks.items()}

    # the main engine's retrace gate closes HERE: the paged/spec legs
    # below build their own (new) models, whose first-build traces are
    # inherent, not retraces
    jits_end = _jit_count()
    stats = entry.stats()

    paged = _paged_sweep(args, rng) if args.paged else None
    spec = _spec_leg(args, rng) if args.spec else None
    sampled = _sample_leg(args, rng) if args.sample_leg else None
    beam = _beam_leg(args, rng) if args.beam_leg else None
    overload = (_overload_leg(args, rng)
                if (args.overload_leg or args.smoke) else None)
    kernel_parity = _kernel_modes_leg(args) if args.smoke else None

    engine.shutdown()
    last = sweep[-1]
    report = {
        "metric": "serving_decode_tokens_per_sec",
        "value": last["tokens_per_sec"],
        "unit": "tok/s",
        "extra": {
            "mode": "decode",
            "slots": args.slots, "max_len": args.max_len,
            "arena_mib": round(stats["arena_mib"], 3),
            "served": served, "errors": errors,
            "offline_mismatches": mismatches,
            "verified_bit_identical": verified,
            "sweep": sweep,
            "warmup_seconds": round(warm_s, 2),
            "retraces_after_warmup": jits_end - jits_warm,
            "compile_sources": stats["compile_sources"],
            "prefix_hits": stats["prefix_hits"],
            "tenant_tokens": stats["tenant_tokens"],
            "tenant_weights": TENANT_WEIGHTS,
            "fairness_mean_completion_rank": mean_rank,
            "latency_p50_s": round(stats["latency_p50_s"], 5),
            "latency_p99_s": round(stats["latency_p99_s"], 5),
            "queue_wait_p99_s": round(stats["queue_wait_p99_s"], 5),
            "decode_step_p99_s": round(stats["decode_step_p99_s"], 5),
        },
    }
    if paged is not None:
        report["extra"]["paged"] = paged
    if spec is not None:
        report["extra"]["spec"] = spec
    if sampled is not None:
        report["extra"]["sample"] = sampled
    if beam is not None:
        report["extra"]["beam"] = beam
    if overload is not None:
        report["extra"]["overload"] = overload
    if kernel_parity is not None:
        report["extra"]["kernel_parity"] = kernel_parity
    print(json.dumps(report))
    if args.smoke:
        assert kernel_parity["bit_identical"], kernel_parity
        assert errors == 0 and served == args.requests * len(args.rates), \
            (served, errors)
        assert mismatches == 0, f"{mismatches} continuous!=offline"
        assert jits_end == jits_warm, \
            f"{jits_end - jits_warm} retraces after warmup"
        assert last["occupancy_gain"] > 1.5, sweep
        if paged is not None:
            for leg in paged["sweep"]:
                assert leg["offline_mismatches"] == 0, leg
            shared = [leg for leg in paged["sweep"]
                      if leg["block_size"] < args.max_len]
            assert any(leg["radix_hits"] > 0 for leg in shared), paged
            assert any(leg["peak_dedup_ratio"] > 1.0 for leg in shared), \
                paged
        if spec is not None:
            assert spec["offline_mismatches"] == 0, spec
            assert spec["steps_per_token"] < 1.0, spec
            assert spec["retraces"] == 0, spec
        if sampled is not None:
            assert sampled["bit_identical"], sampled
            assert sampled["retraces"] == 0, sampled
        if beam is not None:
            assert beam["tokens_bit_identical"], beam
            assert beam["conservation_ok"], beam
            assert beam["beam_forks"] > 0, beam
            assert beam["retraces"] == 0, beam
        if overload is not None:
            p = overload["park"]
            assert overload["bit_identical"], overload
            assert p["failed"] == 0, overload
            assert overload["goodput_admitted"] == 1.0, overload
            assert p["parked"] >= 1 and p["resumed"] >= 1, overload
            assert p["completed"] >= overload["shed_only"]["completed"], \
                overload
            assert p["retraces"] == 0, overload
        print("DECODE_SMOKE_OK")
    return 0


def _kernel_modes_leg(args):
    """Kernel on/off bit-identity gate (PADDLE_TPU_KERNELS): the same
    paged + chunked + speculative workload decoded hand-stepped under
    the registry's "off" (composite fallbacks) and "interpret" (Pallas
    kernels through the interpreter) modes must produce BYTE-identical
    tokens for every request — the fused paged-attention kernel is the
    exact composite primitive sequence, and this is where that contract
    is held against the real engine, not a unit harness."""
    from paddle_tpu import kernels
    from paddle_tpu.serving.decode import GenerationEngine, build_decoder_model

    prompts = [[7, 3, 9, 2, 11, 5, 8, 1, 4], [7, 3, 9, 2, 11, 5, 8, 1],
               [1, 2], [9, 9, 4, 4, 1, 2, 3, 4, 5, 6, 7, 8]]

    def drive(mode):
        with kernels.scoped_mode(mode):
            engine = GenerationEngine(queue_depth=16, breaker_threshold=0)
            geom = dict(vocab_size=args.vocab, hidden=args.hidden,
                        num_layers=args.layers, slots=args.slots,
                        max_len=args.max_len)
            entry = engine.register_model(lambda: build_decoder_model(
                block_size=4, chunk_tokens=4, name="bench_kmode",
                version="1", **geom))
            engine.register_model(lambda: build_decoder_model(
                block_size=4, name="bench_kmode_draft", version="1",
                **geom))
            resps = [engine.submit(p, max_new_tokens=5,
                                    model="bench_kmode") for p in prompts]
            resps.append(engine.submit(
                prompts[0], max_new_tokens=5, model="bench_kmode",
                draft_model="bench_kmode_draft", spec_k=2))
            for _ in range(args.max_len * 4):
                if all(r.done() for r in resps):
                    break
                entry._iterate()
            outs = [[int(t) for t in r.result(timeout=120)["tokens"]]
                    for r in resps]
            engine.shutdown()
            return outs

    off = drive("off")
    interp = drive("interpret")
    return {
        "modes": ["off", "interpret"],
        "requests": len(off),
        "bit_identical": off == interp,
    }


def _sample_leg(args, rng):
    """Committed-threefry sampled decode (r17): the SAME sampled workload
    admitted in TWO shuffled orders must byte-equal the offline
    whole-sequence reference both times — the stream is keyed per
    (request seed, emitted-token index), so batchmates, slots, and
    arrival timing never enter it. Zero retraces: the policy runs on the
    host over the one compiled logits fetch."""
    from paddle_tpu.serving.decode import (
        GenerationEngine,
        SamplingParams,
        build_decoder_model,
    )

    engine = GenerationEngine(queue_depth=args.queue_depth,
                              breaker_threshold=0)
    entry = engine.register_model(lambda: build_decoder_model(
        vocab_size=args.vocab, hidden=args.hidden, num_layers=args.layers,
        slots=args.slots, max_len=args.max_len, block_size=4,
        name="bench_sample", version="1"))
    n = max(args.slots * 2, 8)
    prompts = [[int(t) for t in rng.randint(0, args.vocab,
                                            size=int(rng.randint(1, 6)))]
               for _ in range(n)]
    sp = SamplingParams(temperature=0.9, top_k=8, top_p=0.95, seed=1234)
    refs = [entry.offline_decode(p, 6, sampling=sp) for p in prompts]
    jits0 = _jit_count()
    engine.start()
    identical = True
    for order_seed in (0, 1):
        order = np.random.RandomState(order_seed).permutation(n)
        resps = {}
        for i in order:
            resps[int(i)] = engine.submit(prompts[i], max_new_tokens=6,
                                          sampling=sp)
        outs = [[int(t) for t in resps[i].result(timeout=300)["tokens"]]
                for i in range(n)]
        identical = identical and outs == refs
    st = entry.stats()
    engine.shutdown()
    return {
        "requests": n,
        "admission_orders": 2,
        "params": sp.describe(),
        "bit_identical": identical,
        "sampled_tokens": st["sampled_tokens"],
        "retraces": _jit_count() - jits0,
    }


def _beam_leg(args, rng):
    """Width-3 COW beam search (r17): every ranked hypothesis byte-equals
    the offline beam reference; forks/prunes are counted and the block
    pool's free/cached/live partition is re-asserted after retirement
    (conservation across fork = refcount++ / prune = release)."""
    from paddle_tpu.serving.decode import (
        BeamParams,
        GenerationEngine,
        build_decoder_model,
    )

    engine = GenerationEngine(queue_depth=args.queue_depth,
                              breaker_threshold=0)
    entry = engine.register_model(lambda: build_decoder_model(
        vocab_size=args.vocab, hidden=args.hidden, num_layers=args.layers,
        slots=args.slots, max_len=args.max_len, block_size=4, eos_id=0,
        name="bench_beam", version="1"))
    prompts = [[int(t) for t in rng.randint(1, args.vocab,
                                            size=int(rng.randint(2, 6)))]
               for _ in range(4)]
    refs = [entry.offline_beam(p, 6, BeamParams(3)) for p in prompts]
    jits0 = _jit_count()
    engine.start()
    identical = True
    for p, ref in zip(prompts, refs):
        got = engine.submit(p, max_new_tokens=6,
                            beam_width=3).result(timeout=300)
        identical = identical and (
            [[int(t) for t in h["tokens"]] for h in got["beams"]]
            == [list(rt) for rt, _rs in ref])
    entry.block_pool.check_conservation()
    st = entry.stats()
    conserved = st["block_pool"]["blocks_live"] == 0
    engine.shutdown()
    return {
        "requests": len(prompts),
        "width": 3,
        "tokens_bit_identical": identical,
        "beam_forks": st["beam_forks"],
        "beam_prunes": st["beam_prunes"],
        "beam_finished": st["beam_finished"],
        "conservation_ok": conserved,
        "retraces": _jit_count() - jits0,
    }


def _paged_sweep(args, rng):
    """Block-size sweep over a SHARE-HEAVY workload (half the prompts
    extend one common prefix): small blocks let the radix tree dedup
    physical storage; block_size == max_len is the degenerate slotted
    design (one block per slot, zero sharing possible beyond whole-slot
    geometry). Mid-flight pool state is sampled hand-stepped (no
    scheduler thread) so the dedup numbers are deterministic."""
    from paddle_tpu.serving.decode import GenerationEngine, build_decoder_model

    out = []
    for bs in (4, args.max_len):
        engine = GenerationEngine(queue_depth=args.queue_depth,
                                  breaker_threshold=0)
        entry = engine.register_model(lambda bs=bs: build_decoder_model(
            vocab_size=args.vocab, hidden=args.hidden,
            num_layers=args.layers, slots=args.slots, max_len=args.max_len,
            block_size=bs, name=f"bench_paged{bs}", version="1",
        ))
        shared_prefix = [int(t) for t in rng.randint(0, args.vocab, size=8)]
        reqs = []
        for i in range(args.slots):
            if i % 2 == 0:
                prompt = shared_prefix + [int(rng.randint(0, args.vocab))]
            else:
                prompt = [int(t) for t in
                          rng.randint(0, args.vocab,
                                      size=int(rng.randint(2, 6)))]
            reqs.append((prompt, 6))
        refs = [entry.offline_decode(p, n) for p, n in reqs]
        resps = [engine.submit(p, max_new_tokens=n) for p, n in reqs]
        entry._admit_free_slots()
        mid = entry.block_pool.stats()          # sampled while live
        for _ in range(args.max_len):
            if all(r.done() for r in resps):
                break
            entry._step()
        mism = sum(
            1 for r, ref in zip(resps, refs)
            if [int(t) for t in r.result(timeout=120)["tokens"]] != ref)
        st = entry.stats()
        out.append({
            "block_size": bs,
            "num_blocks": entry.model.num_blocks,
            "arena_mib": round(st["arena_mib"], 3),
            "slotted_equivalent_mib":
                round(st["slotted_equivalent_mib"], 3),
            "peak_occupancy": round(mid["occupancy"], 3),
            "peak_dedup_ratio": round(mid["dedup_ratio"], 3),
            "radix_hits": mid["radix_hits"],
            "cow_copies": st["block_pool"]["cow_copies"],
            "offline_mismatches": mism,
        })
        engine.shutdown()
    return {"sweep": out}


def _spec_leg(args, rng):
    """Speculative decoding on a repeat-heavy workload: draft = a second
    registry entry with the TARGET's geometry (deterministic init makes
    the weights byte-identical — the acceptance upper bound, and the
    honest way to measure the machinery without a trained draft), plus a
    distinct-geometry draft leg whose acceptance is reported unasserted."""
    from paddle_tpu.serving.decode import GenerationEngine, build_decoder_model

    engine = GenerationEngine(queue_depth=args.queue_depth,
                              breaker_threshold=0)
    geom = dict(vocab_size=args.vocab, hidden=args.hidden,
                num_layers=args.layers, slots=args.slots,
                max_len=args.max_len)
    tgt = engine.register_model(lambda: build_decoder_model(
        name="bench_spec_t", version="1", **geom))
    engine.register_model(lambda: build_decoder_model(
        name="bench_spec_d", version="1", **geom))
    engine.register_model(lambda: build_decoder_model(
        name="bench_spec_d1", version="1", **{**geom, "num_layers": 1}))
    # repeat-heavy prompts: short cycles the greedy head locks onto
    base = [int(t) for t in rng.randint(0, args.vocab, size=2)]
    reqs = [(base * 2, 12), (base * 3, 10), (base * 2 + [1], 12),
            (base * 2, 12)]
    refs = [tgt.offline_decode(p, n) for p, n in reqs]
    jits0 = _jit_count()
    engine.start()
    resps = [engine.submit(p, model="bench_spec_t", max_new_tokens=n,
                           draft_model="bench_spec_d", spec_k=3)
             for p, n in reqs]
    mism = sum(
        1 for r, ref in zip(resps, refs)
        if [int(t) for t in r.result(timeout=300)["tokens"]] != ref)
    st = tgt.stats()
    identical = {
        "steps_per_token": round(st["spec_steps_per_token"], 3),
        "acceptance_rate": round(st["spec_acceptance_rate"], 3),
    }
    # distinct-draft leg: acceptance is a property of the models, so it
    # is REPORTED, never gated
    d_resps = [engine.submit(p, model="bench_spec_t", max_new_tokens=n,
                             draft_model="bench_spec_d1", spec_k=3)
               for p, n in reqs[:2]]
    mism += sum(
        1 for r, ref in zip(d_resps, refs[:2])
        if [int(t) for t in r.result(timeout=300)["tokens"]] != ref)
    st2 = tgt.stats()
    engine.shutdown()
    return {
        "spec_k": 3,
        "steps_per_token": identical["steps_per_token"],
        "acceptance_rate": identical["acceptance_rate"],
        "distinct_draft_acceptance_rate": round(
            (st2["spec_accepted_tokens"] - st["spec_accepted_tokens"])
            / max(st2["spec_proposed_tokens"]
                  - st["spec_proposed_tokens"], 1), 3),
        "target_steps": st2["spec_target_steps"],
        "emitted_tokens": st2["spec_emitted_tokens"],
        "offline_mismatches": mism,
        "retraces": _jit_count() - jits0,
    }


def _overload_leg(args, rng):
    """r18 graceful-degradation leg: the SAME 2x-overload open-loop
    burst through an undersized block pool (12 rows, 2 slots), once
    with the host KV tier enabled (exhaustion parks, sessions resume)
    and once with it zeroed (parking impossible — the shed-only
    baseline where mid-generation exhaustion fails the request). The
    park leg must lose NOTHING it admitted (goodput-of-admitted 1.0,
    every completion bit-identical to offline) and complete at least as
    many requests as the shed-only baseline, with zero retraces — the
    spill/re-inject path reuses the admission inject/prefill
    executables. The brownout ladder runs hot through the burst; its
    transition log is returned as the overload witness."""
    from paddle_tpu.serving.decode import GenerationEngine, build_decoder_model

    n = 8
    prompts = [[int(t) for t in rng.randint(0, args.vocab, size=4)]
               for _ in range(n)]

    def drive(host_tier_mb, name):
        engine = GenerationEngine(queue_depth=n * 2 + 8,
                                  breaker_threshold=0,
                                  host_tier_mb=host_tier_mb)
        entry = engine.register_model(lambda: build_decoder_model(
            vocab_size=args.vocab, hidden=args.hidden,
            num_layers=args.layers, slots=2, max_len=16, block_size=2,
            num_blocks=6, name=name, version="1"))
        refs = [entry.offline_decode(p, 6) for p in prompts]
        engine.start()
        # warm: one request per slot drained, then close the jit gate
        for r in [engine.submit([1, 2], max_new_tokens=2)
                  for _ in range(2)]:
            r.result(timeout=120)
        jits0 = _jit_count()
        resps = []
        shed = 0
        for p in prompts:
            time.sleep(0.001)
            try:
                resps.append(engine.submit(p, max_new_tokens=6))
            except Exception:
                resps.append(None)     # brownout shed at the door
                shed += 1
        completed = failed = mismatches = 0
        for r, ref in zip(resps, refs):
            if r is None:
                continue
            try:
                out = [int(t) for t in r.result(timeout=300)["tokens"]]
                completed += 1
                if out != ref:
                    mismatches += 1
            except Exception:
                failed += 1
        st = entry.stats()
        engine.shutdown()
        return {
            "admitted": n - shed, "shed": shed,
            "completed": completed, "failed": failed,
            "mismatches": mismatches,
            "parked": st["sessions_parked"],
            "resumed": st["sessions_resumed"],
            "resume_replays": st["resume_replays"],
            "host_tier": {k: st["host_tier"][k]
                          for k in ("spills", "writebacks", "hits",
                                    "rejected")},
            "brownout_transitions":
                len(st["brownout"]["transitions"]),
            "brownout_peak": max(
                [t["to"] for t in st["brownout"]["transitions"]],
                default=0),
            "retraces": _jit_count() - jits0,
        }

    park = drive(64, "bench_ov")
    shed_only = drive(0, "bench_ov_shed")
    return {
        "requests": n,
        "park": park,
        "shed_only": shed_only,
        "goodput_admitted": round(
            park["completed"] / max(park["admitted"], 1), 3),
        "bit_identical": park["mismatches"] == 0,
    }


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--mode", choices=("closed", "open"), default="closed")
    ap.add_argument("--requests", type=int, default=512)
    ap.add_argument("--clients", type=int, default=8)
    ap.add_argument("--rate", type=float, default=200.0,
                    help="open-loop offered load, req/s")
    ap.add_argument("--replicas", type=int, default=2)
    ap.add_argument("--max-batch", type=int, default=8)
    ap.add_argument("--seq", type=int, default=0,
                    help="max padded-axis length (0 = fixed-shape model)")
    ap.add_argument("--feat", type=int, default=8)
    ap.add_argument("--queue-depth", type=int, default=512)
    ap.add_argument("--max-wait-ms", type=float, default=5.0)
    ap.add_argument("--deadline-ms", type=float, default=0.0)
    ap.add_argument("--decode", action="store_true",
                    help="continuous-batching decode over GenerationEngine")
    ap.add_argument("--slots", type=int, default=8,
                    help="decode: KV arena slots (the iteration batch)")
    ap.add_argument("--max-len", type=int, default=64,
                    help="decode: KV arena length per slot")
    ap.add_argument("--vocab", type=int, default=64)
    ap.add_argument("--hidden", type=int, default=16)
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--rates", type=str, default=None,
                    help="decode: comma-separated arrival-rate sweep, req/s")
    ap.add_argument("--paged", action="store_true",
                    help="decode: block-size sweep (pool occupancy, "
                         "radix dedup, COW) on a share-heavy workload")
    ap.add_argument("--spec", action="store_true",
                    help="decode: speculative-decoding leg "
                         "(steps-per-token, acceptance rate)")
    ap.add_argument("--sample", dest="sample_leg", action="store_true",
                    help="decode: committed-threefry sampled leg "
                         "(shuffled-admission replay bit-identity)")
    ap.add_argument("--beam", dest="beam_leg", action="store_true",
                    help="decode: COW beam-search leg (offline "
                         "reference bit-identity + block conservation)")
    ap.add_argument("--overload", dest="overload_leg",
                    action="store_true",
                    help="decode: r18 degradation leg (park/resume vs "
                         "shed-only goodput under a 2x open-loop burst)")
    ap.add_argument("--verify", type=int, default=8,
                    help="decode: requests/rate checked against offline "
                         "(--smoke checks every request)")
    ap.add_argument("--smoke", action="store_true",
                    help="seconds-scale run + invariant asserts (CI)")
    args = ap.parse_args(argv)
    if args.smoke:
        args.requests, args.clients, args.replicas = 32, 4, 1
        args.max_batch = 4
    if args.decode:
        if args.smoke:
            args.requests, args.slots, args.max_len = 48, 4, 24
            args.vocab, args.hidden, args.layers = 32, 8, 2
            args.rates = args.rates or "500"
        args.rates = [float(r) for r in
                      (args.rates or str(args.rate)).split(",")]

    from paddle_tpu.core.places import ensure_backend_or_cpu

    on_tpu, diag = ensure_backend_or_cpu()

    if args.decode:
        return run_decode(args, np.random.RandomState(0))

    from paddle_tpu import inference
    from paddle_tpu.serving import BucketLattice, ServingEngine

    with tempfile.TemporaryDirectory() as tmp:
        model_dir = _save_model(tmp, feat=args.feat, seq=args.seq)
        config = inference.Config(model_dir)
        if not on_tpu:
            config.disable_tpu()
        lattice = BucketLattice.pow2(args.max_batch, args.seq or None,
                                     min_seq=2)
        config.set_serving_buckets(lattice.batch_sizes, lattice.seq_lens)
        engine = ServingEngine(
            config, lattice=lattice, num_replicas=args.replicas,
            queue_depth=args.queue_depth, max_wait_ms=args.max_wait_ms,
        )
        t0 = time.perf_counter()
        engine.start()
        warm_s = time.perf_counter() - t0

        rng = np.random.RandomState(0)
        runner = run_closed if args.mode == "closed" else run_open
        served, errors, wall = runner(engine, args, rng)
        stats = engine.stats()
        engine.shutdown()

    report = {
        "metric": f"serving_{args.mode}_loop_requests_per_sec",
        "value": round(served / max(wall, 1e-9), 1),
        "unit": "req/s",
        "extra": {
            "device": "tpu" if on_tpu else "cpu",
            "backend_diag": diag,
            "served": served,
            "rejected": stats["rejected"],
            "deadline_missed": stats["deadline_missed"],
            "error_codes": sorted(set(errors)),
            "warmup_seconds": round(warm_s, 2),
            "avg_batch_rows": round(stats["avg_batch_rows"], 2),
            "avg_batch_occupancy": round(stats["avg_batch_occupancy"], 3),
            "queue_wait_p50_s": round(stats["queue_wait_p50_s"], 5),
            "queue_wait_p99_s": round(stats["queue_wait_p99_s"], 5),
            "latency_p50_s": round(stats["latency_p50_s"], 5),
            "latency_p99_s": round(stats["latency_p99_s"], 5),
            "cache_hit_rate": stats["cache_hit_rate"],
            "replicas": args.replicas,
            "mode": args.mode,
        },
    }
    print(json.dumps(report))
    if args.smoke:
        assert served == args.requests, (served, args.requests, errors)
        assert stats["cache_hit_rate"] == 1.0, stats
        assert stats["cache_misses"] == 0, stats
        print("SERVING_SMOKE_OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
