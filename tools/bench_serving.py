#!/usr/bin/env python
"""Serving load generator: closed-loop and open-loop over ServingEngine.

Closed loop (`--mode closed`): N concurrent clients, each submitting its
next request the moment the previous one returns — measures saturated
throughput and the batcher's coalescing gain. Open loop (`--mode open`):
Poisson arrivals at `--rate` req/s regardless of completions — measures
SLO behavior under offered load, including explicit backpressure
(rejections counted, not retried). Both report one JSON line:
throughput, p50/p99 queue+total latency, mean batch occupancy,
rejection/deadline counters, and the post-warmup compile-cache hit rate
(anything < 1.0 means the bucket lattice is mis-sized for the traffic).

`--smoke` runs a seconds-scale configuration and asserts the invariants
(all served, zero retrace) — wired into tier-1 CI by
tests/test_serving.py.

Usage:
  python tools/bench_serving.py [--mode closed|open] [--requests 512]
      [--clients 8] [--rate 200] [--replicas 2] [--max-batch 8]
      [--seq 0] [--deadline-ms 0] [--smoke]
"""

import argparse
import json
import os
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def _save_model(tmpdir, feat=8, seq=0):
    """Tiny fc stack; with --seq a per-token head over a [-1, -1, feat]
    input (the padded-axis path)."""
    import paddle_tpu as fluid
    from paddle_tpu.core.ir import Program, program_guard

    main, startup = Program(), Program()
    with program_guard(main, startup):
        if seq:
            x = fluid.data("x", [-1, -1, feat])
            h = fluid.layers.fc(x, 16, act="relu", num_flatten_dims=2)
            pred = fluid.layers.fc(h, 4, num_flatten_dims=2)
        else:
            x = fluid.data("x", [-1, feat])
            h = fluid.layers.fc(x, 16, act="relu")
            pred = fluid.layers.fc(h, 4)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        model_dir = os.path.join(tmpdir, "model")
        fluid.io.save_inference_model(model_dir, ["x"], [pred], exe,
                                      main_program=main)
    return model_dir


def _make_request(rng, args):
    rows = int(rng.randint(1, 3))
    if args.seq:
        ln = int(rng.randint(2, args.seq + 1))
        return {"x": rng.randn(rows, ln, args.feat).astype("float32")}
    return {"x": rng.randn(rows, args.feat).astype("float32")}


def run_closed(engine, args, rng):
    from paddle_tpu.serving import ServingError

    lock = threading.Lock()
    served, errors = [], []
    per_client = args.requests // args.clients

    def client(cid):
        crng = np.random.RandomState(1000 + cid)
        for i in range(per_client):
            try:
                resp = engine.submit(
                    _make_request(crng, args), priority=i % 3,
                    deadline_ms=args.deadline_ms or None,
                )
                out = resp.result(timeout=120)
                with lock:
                    served.append(out)
            except ServingError as e:
                with lock:
                    errors.append(e.code)

    threads = [threading.Thread(target=client, args=(c,))
               for c in range(args.clients)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return len(served), errors, time.perf_counter() - t0


def run_open(engine, args, rng):
    from paddle_tpu.serving import ServingError

    responses, errors = [], []
    t0 = time.perf_counter()
    for i in range(args.requests):
        time.sleep(float(rng.exponential(1.0 / args.rate)))
        try:
            responses.append(engine.submit(
                _make_request(rng, args), priority=i % 3,
                deadline_ms=args.deadline_ms or None,
            ))
        except ServingError as e:
            errors.append(e.code)
    served = 0
    for r in responses:
        try:
            r.result(timeout=120)
            served += 1
        except ServingError as e:
            errors.append(e.code)
    return served, errors, time.perf_counter() - t0


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--mode", choices=("closed", "open"), default="closed")
    ap.add_argument("--requests", type=int, default=512)
    ap.add_argument("--clients", type=int, default=8)
    ap.add_argument("--rate", type=float, default=200.0,
                    help="open-loop offered load, req/s")
    ap.add_argument("--replicas", type=int, default=2)
    ap.add_argument("--max-batch", type=int, default=8)
    ap.add_argument("--seq", type=int, default=0,
                    help="max padded-axis length (0 = fixed-shape model)")
    ap.add_argument("--feat", type=int, default=8)
    ap.add_argument("--queue-depth", type=int, default=512)
    ap.add_argument("--max-wait-ms", type=float, default=5.0)
    ap.add_argument("--deadline-ms", type=float, default=0.0)
    ap.add_argument("--smoke", action="store_true",
                    help="seconds-scale run + invariant asserts (CI)")
    args = ap.parse_args(argv)
    if args.smoke:
        args.requests, args.clients, args.replicas = 32, 4, 1
        args.max_batch = 4

    from paddle_tpu.core.places import ensure_backend_or_cpu

    on_tpu, diag = ensure_backend_or_cpu()

    from paddle_tpu import inference
    from paddle_tpu.serving import BucketLattice, ServingEngine

    with tempfile.TemporaryDirectory() as tmp:
        model_dir = _save_model(tmp, feat=args.feat, seq=args.seq)
        config = inference.Config(model_dir)
        if not on_tpu:
            config.disable_tpu()
        lattice = BucketLattice.pow2(args.max_batch, args.seq or None,
                                     min_seq=2)
        config.set_serving_buckets(lattice.batch_sizes, lattice.seq_lens)
        engine = ServingEngine(
            config, lattice=lattice, num_replicas=args.replicas,
            queue_depth=args.queue_depth, max_wait_ms=args.max_wait_ms,
        )
        t0 = time.perf_counter()
        engine.start()
        warm_s = time.perf_counter() - t0

        rng = np.random.RandomState(0)
        runner = run_closed if args.mode == "closed" else run_open
        served, errors, wall = runner(engine, args, rng)
        stats = engine.stats()
        engine.shutdown()

    report = {
        "metric": f"serving_{args.mode}_loop_requests_per_sec",
        "value": round(served / max(wall, 1e-9), 1),
        "unit": "req/s",
        "extra": {
            "device": "tpu" if on_tpu else "cpu",
            "backend_diag": diag,
            "served": served,
            "rejected": stats["rejected"],
            "deadline_missed": stats["deadline_missed"],
            "error_codes": sorted(set(errors)),
            "warmup_seconds": round(warm_s, 2),
            "avg_batch_rows": round(stats["avg_batch_rows"], 2),
            "avg_batch_occupancy": round(stats["avg_batch_occupancy"], 3),
            "queue_wait_p50_s": round(stats["queue_wait_p50_s"], 5),
            "queue_wait_p99_s": round(stats["queue_wait_p99_s"], 5),
            "latency_p50_s": round(stats["latency_p50_s"], 5),
            "latency_p99_s": round(stats["latency_p99_s"], 5),
            "cache_hit_rate": stats["cache_hit_rate"],
            "replicas": args.replicas,
            "mode": args.mode,
        },
    }
    print(json.dumps(report))
    if args.smoke:
        assert served == args.requests, (served, args.requests, errors)
        assert stats["cache_hit_rate"] == 1.0, stats
        assert stats["cache_misses"] == 0, stats
        print("SERVING_SMOKE_OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
