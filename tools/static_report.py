#!/usr/bin/env python
"""STATIC_EVIDENCE_r09 generator: static sharding predictions vs live HLO.

Round 9's claim is that the collective story PR 7 proved by compiling and
grepping HLO is *statically decidable*: analysis/sharding.py predicts —
without XLA in the loop — which collectives a (program, mesh, layout)
triple will pay and how many bytes each moves. This tool makes that claim
falsifiable the r07 way: for each evidence arm (registry tp tiny-BERT,
registry dp×fsdp×tp, MEGATRON_RULES control) it records

  static:  the analyzer's resharding report — per-kind byte accounting,
           predicted weight-sized collectives (shape + bytes + cause),
           and the --budget-kb verdict
  live:    the same program actually lowered on the 8-virtual-device mesh
           (utils/hlo.py builders, identical geometry to r07), with
           weight_shaped_collectives + collective_byte_report
  match:   every live weight-shaped collective resolved against a static
           prediction of the same shape, byte ratio recorded (the
           acceptance bound is 2x)

plus the static peak-HBM estimates (donate on/off) for the examples/
programs. tests/test_hlo.py::test_static_evidence_r09_committed re-derives
the live half and tools/lint_program.py smoke re-derives the static half,
so neither side can drift silently.

Usage: python tools/static_report.py [--out STATIC_EVIDENCE_r09.json]
       (~3 min on the CPU rig; the static half alone is seconds)
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

GEOMETRY = {"batch": 8, "seq_len": 24, "max_pred": 20}
BUDGET_KB = 192  # separates activation-class (<=160 KB live max on the
# registry arms) from the control's 256 KB full-weight gathers


def _arms():
    from paddle_tpu.parallel.env import make_mesh
    from paddle_tpu.parallel.sharding import MEGATRON_RULES
    from paddle_tpu.parallel.spec_layout import SpecLayout

    return {
        "tp_registry": dict(
            mesh=make_mesh((2, 4), ("data", "model")),
            mesh_spec=((2, 4), ("data", "model")),
            spec_layout=SpecLayout(), param_rules=None,
        ),
        "dp_fsdp_tp_registry": dict(
            mesh=make_mesh((2, 2, 2), ("data", "fsdp", "model")),
            mesh_spec=((2, 2, 2), ("data", "fsdp", "model")),
            spec_layout=SpecLayout(), param_rules=None,
        ),
        "megatron_control": dict(
            mesh=make_mesh((2, 4), ("data", "model")),
            mesh_spec=((2, 4), ("data", "model")),
            spec_layout=None, param_rules=MEGATRON_RULES,
        ),
    }


def _evidence_program():
    """The r07 evidence program + synthetic feed shapes + param shapes —
    built ONCE and shared by the static and live halves."""
    import numpy as np

    from paddle_tpu.models import bert

    cfg = bert.BertConfig.tiny()
    cfg.hidden_dropout_prob = 0.0
    cfg.attention_probs_dropout_prob = 0.0
    main, startup, feeds, fetches = bert.build_bert_pretrain(
        cfg, seq_len=GEOMETRY["seq_len"], lr=1e-3,
        max_predictions_per_seq=GEOMETRY["max_pred"],
    )
    data = bert.synthetic_batch(
        np.random.RandomState(0), GEOMETRY["batch"], GEOMETRY["seq_len"],
        cfg, max_predictions_per_seq=GEOMETRY["max_pred"],
    )
    feed_shapes = {k: tuple(np.asarray(v).shape) for k, v in data.items()}
    from paddle_tpu.analysis.sharding import weight_param_shapes

    return main, feed_shapes, weight_param_shapes(main)


def static_sections():
    """arm -> static prediction summary (the half the smoke gate
    re-derives; NO lowering happens here)."""
    from paddle_tpu.analysis.sharding import (
        analyze_sharding,
        collective_budget_diagnostics,
        weight_sized_events,
    )

    main, feed_shapes, param_shapes = _evidence_program()
    out = {}
    for tag, arm in _arms().items():
        rep = analyze_sharding(
            main, arm["mesh"], spec_layout=arm["spec_layout"],
            param_rules=arm["param_rules"], feed_shapes=feed_shapes,
        )
        ws = weight_sized_events(rep, param_shapes)
        over = collective_budget_diagnostics(rep, BUDGET_KB * 1024)
        shape_counts = {}
        for e in ws:
            key = "x".join(map(str, e.shape))
            shape_counts[key] = shape_counts.get(key, 0) + 1
        out[tag] = {
            "events": len(rep.events),
            "by_kind": rep.by_kind(),
            "max_bytes": rep.max_bytes(),
            "total_bytes": rep.total_bytes(),
            "weight_sized_count": len(ws),
            "weight_sized_shapes": dict(sorted(shape_counts.items())),
            "weight_sized": [e.to_json() for e in ws],
            "budget_kb": BUDGET_KB,
            "budget_verdict": "fail" if over else "pass",
            "over_budget": len(over),
        }
    return out


def live_sections():
    """arm -> live HLO ground truth (the half the evidence test
    re-derives; lowers each arm on the virtual mesh, minutes)."""
    from collections import Counter

    from paddle_tpu.utils import hlo

    out = {}
    geo = dict(seq_len=GEOMETRY["seq_len"], max_pred=GEOMETRY["max_pred"],
               with_param_shapes=True)
    for tag, arm in _arms().items():
        shape, axes = arm["mesh_spec"]
        txt, shapes = hlo.tiny_bert_parallel_text(
            shape, axes, param_rules=arm["param_rules"],
            spec_layout=arm["spec_layout"], **geo,
        )
        offenders = hlo.weight_shaped_collectives(txt, shapes)
        counts = Counter(
            (kind, "x".join(map(str, s))) for kind, s, _l in offenders
        )
        rep = hlo.collective_byte_report(txt)
        out[tag] = {
            "weight_shaped_count": len(offenders),
            "weight_shaped": [
                {"kind": k, "shape": s, "count": n}
                for (k, s), n in sorted(counts.items())
            ],
            "collectives": hlo.count_collectives(txt),
            "max_bytes": rep["max_bytes"],
            "by_kind": rep["by_kind"],
        }
    return out


def match_sections(static, live):
    """Resolve every live weight-shaped collective against a static
    prediction of the same full shape; byte ratios must be within 2x."""
    out = {}
    for tag in static:
        s, l = static[tag], live[tag]
        matches, unmatched = [], []
        for ent in l["weight_shaped"]:
            shape = tuple(int(d) for d in ent["shape"].split("x"))
            nbytes = 1
            for d in shape:
                nbytes *= d
            nbytes *= 4  # the evidence programs train f32 master state
            preds = [e for e in s["weight_sized"]
                     if tuple(e["shape"] or ()) == shape and e["bytes"]]
            if not preds:
                unmatched.append(ent)
                continue
            best = min(preds, key=lambda e: abs(e["bytes"] - nbytes))
            ratio = max(best["bytes"], nbytes) / max(
                min(best["bytes"], nbytes), 1)
            matches.append({
                "shape": ent["shape"], "live_kind": ent["kind"],
                "live_count": ent["count"], "live_bytes": nbytes,
                "static_cause": best["cause"],
                "static_bytes": best["bytes"],
                "byte_ratio": round(ratio, 4),
            })
        out[tag] = {
            "live_collectives_matched": len(matches),
            "live_collectives_unmatched": len(unmatched),
            "unmatched": unmatched,
            "max_byte_ratio": max(
                (m["byte_ratio"] for m in matches), default=1.0),
            "matches": matches,
        }
    return out


def example_memory_section():
    """Static peak-HBM estimates for the examples/ programs (donate
    on/off) — the numbers tests/test_static_analysis.py bounds against
    runtime-observed live bytes."""
    import importlib.util

    from paddle_tpu.analysis.memory import estimate_peak_hbm
    from paddle_tpu.passes import (
        apply_deferred_sharded_embedding_rewrite,
        apply_deferred_sparse_rewrite,
    )

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = {}
    for name in ("fit_a_line", "recognize_digits", "recommender_system"):
        spec = importlib.util.spec_from_file_location(
            f"sr_example_{name}", os.path.join(repo, "examples",
                                               f"{name}.py")
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        built = mod.build_programs()
        main, feed_names, fetches = built[0], built[2], built[3]
        apply_deferred_sparse_rewrite(main)
        apply_deferred_sharded_embedding_rewrite(main)
        fetch_names = [f if isinstance(f, str) else f.name for f in fetches]
        # bind the symbolic batch dims (batch 16) so every intermediate
        # has a concrete size
        feed_shapes = {}
        block = main.global_block()
        for fname in feed_names:
            v = block._find_var_recursive(fname)
            if v is not None and v.shape is not None:
                feed_shapes[fname] = tuple(
                    16 if d is None or d < 0 else int(d) for d in v.shape
                )
        on = estimate_peak_hbm(main, feed_shapes=feed_shapes,
                               fetch_names=fetch_names, donate=True)
        off = estimate_peak_hbm(main, feed_shapes=feed_shapes,
                                fetch_names=fetch_names, donate=False)
        out[name] = {
            "peak_donate_bytes": on.peak_total_bytes,
            "peak_no_donate_bytes": off.peak_total_bytes,
            "persistent_bytes": on.persistent_bytes,
            "unknown_vars": len(on.unknown_vars),
        }
    return out


def build_report(with_live=True):
    static = static_sections()
    report = {
        "geometry": GEOMETRY,
        "budget_kb": BUDGET_KB,
        "arms": {tag: {"static": sec} for tag, sec in static.items()},
        "example_peak_hbm": example_memory_section(),
    }
    if with_live:
        live = live_sections()
        match = match_sections(static, live)
        for tag in report["arms"]:
            report["arms"][tag]["live"] = live[tag]
            report["arms"][tag]["match"] = match[tag]
    return report


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=None,
                    help="also write the JSON report to this path")
    ap.add_argument("--static-only", action="store_true",
                    help="skip the live HLO recompute (seconds, not "
                    "minutes; the smoke gate's mode)")
    args = ap.parse_args()
    report = build_report(with_live=not args.static_only)
    text = json.dumps(report, indent=1)
    print(text)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text + "\n")


if __name__ == "__main__":
    main()
