"""Transformer-big beam-search inference throughput (BASELINE workload 4).

Bucketed AOT serving at the real 37k vocab: warm every length bucket, then
stream mixed-length batches and report generated tokens/s. On the chip this
runs the big config; the CPU fallback shrinks depth (same code path).

Usage: python tools/bench_transformer_infer.py [batch] [beam]
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main():
    from paddle_tpu.core.places import ensure_backend_or_cpu

    on_tpu, diag = ensure_backend_or_cpu()

    import paddle_tpu as fluid
    from paddle_tpu.models import transformer as tfm

    batch = int(sys.argv[1]) if len(sys.argv) > 1 else (32 if on_tpu else 4)
    beam = int(sys.argv[2]) if len(sys.argv) > 2 else 4
    if on_tpu:
        cfg = tfm.TransformerConfig.big()
        cfg.max_len = 64
        buckets = (16, 32, 64)
        rounds = 8
    else:
        cfg = tfm.TransformerConfig(
            vocab_size=37000, d_model=128, n_heads=4, d_ffn=256,
            n_enc_layers=2, n_dec_layers=2, max_len=32,
        )
        buckets = (8, 16)
        rounds = 3

    main_prog, startup, feeds, fetches = tfm.build_wmt_train(
        cfg, src_len=16, tgt_len=16
    )
    exe = fluid.Executor(fluid.TPUPlace(0))
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        params = tfm.params_from_scope(cfg, scope)

    tr = tfm.BucketedBeamTranslator(
        cfg, params, beam_size=beam, src_buckets=buckets, batch_size=batch
    )
    t0 = time.perf_counter()
    tr.warmup(batch)
    warm_s = time.perf_counter() - t0

    rng = np.random.RandomState(0)
    for _ in range(rounds):
        for b in buckets:
            L = max(2, b - rng.randint(0, b // 2))
            src = rng.randint(3, cfg.vocab_size, (batch, L)).astype("int64")
            tr.translate(src)
    print(json.dumps({
        "metric": "transformer_beam_infer_tokens_per_sec",
        "value": round(tr.tokens_per_sec(), 1),
        "unit": "tokens/s",
        "extra": {
            "device": "tpu" if on_tpu else "cpu",
            "backend_diag": diag,
            "vocab": cfg.vocab_size,
            "beam": beam,
            "batch": batch,
            "buckets": list(buckets),
            "warmup_seconds": round(warm_s, 1),
            "bucket_hits": tr.stats["bucket_hits"],
            "sentences": tr.stats["sentences"],
        },
    }))


if __name__ == "__main__":
    main()
