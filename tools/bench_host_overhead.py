"""Executor host-dispatch overhead: per-step Python cost of exe.run.

A trivial one-op program (scale of a [1] tensor) makes device time
negligible, so the steady-state wall time per step IS the host path:
compile-cache hit, feed signature hash, scope reads through the committed
fast path, donation bookkeeping, fetch conversion. PROFILE.md's round-2
finding was ~200 device_puts per step costing milliseconds; the committed
-scope design (core/executor.py _committed) is what this measures.

Prints one JSON line with per-step microseconds for a param-light and a
param-heavy (200 persistables) program.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

import paddle_tpu as fluid  # noqa: E402


def measure(n_params, steps=300):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.data("x", shape=[1], dtype="float32")
        acc = None
        for i in range(n_params):
            w = fluid.layers.tensor.create_global_var(
                shape=[4], value=float(i), dtype="float32",
                persistable=True, name=f"w_{i}",
            )
            term = fluid.layers.reduce_sum(w)
            acc = term if acc is None else acc + term
        y = fluid.layers.scale(x, scale=2.0)
        out = y + acc if acc is not None else y
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    feed = {"x": np.ones(1, np.float32)}
    with fluid.scope_guard(scope):
        exe.run(startup)
        for _ in range(5):  # compile + commit
            exe.run(main, feed=feed, fetch_list=[out.name],
                    return_numpy=False)
        t0 = time.perf_counter()
        for _ in range(steps):
            r = exe.run(main, feed=feed, fetch_list=[out.name],
                        return_numpy=False)
        np.asarray(r[0])
        dt = time.perf_counter() - t0
    return dt / steps * 1e6


def main():
    light = measure(0)
    heavy = measure(200)
    print(json.dumps({
        "metric": "executor_host_overhead_us_per_step",
        "light_program_us": round(light, 1),
        "heavy_200_persistables_us": round(heavy, 1),
        "per_persistable_ns": round((heavy - light) / 200 * 1e3, 1),
        "note": "steady-state dispatch cost; committed-scope fast path "
                "(core/executor.py _committed) keeps the per-persistable "
                "cost to a type check, not a device_put",
    }))


if __name__ == "__main__":
    main()
