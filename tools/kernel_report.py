#!/usr/bin/env python
"""KERNEL_EVIDENCE_r15: the Pallas kernel registry's claims, derivable on
demand (the PR 6/9/13 discipline — HLO structure, static analysis and
deterministic counters, never wall-clock on this TPU-less rig).

Five claims:

1. **registry** — every registered kernel/policy with its parity
   contract; the CI gate (tests/test_kernels.py::test_kernel_parity)
   parametrizes over this exact enumeration, so a kernel without an
   interpret-mode parity test cannot exist.
2. **amp_flash** — the bf16-AMP BERT step traced through the flash
   kernel (interpret mode: the Pallas body lands in the StableHLO)
   contains ZERO dots with a full-precision operand and ZERO [S, S]
   buffers — the HLO_EVIDENCE checks, extended to the kernel path.
3. **paged_hbm** — analysis/memory.py peak-HBM of the r13 decode
   geometry (8 slots / 32k context / 16 layers, paged at ~2k tokens):
   under KERNEL-path accounting the dense [S, L, H] gather views are
   gone and the peak reduction beats the composite-path 6.9x committed
   in DECODE_EVIDENCE_r13.json, toward the 12.8x arena bound.
4. **embedding_admission** — a deterministic two-leg train stream:
   the device-admission leg performs ZERO host capacity-slab
   round-trips (counter-asserted), the legacy control fires the
   counter, and both host tiers are BIT-identical.
5. **remat** — static peak-HBM of one model under remat policies
   (kernels/remat.py): full < dots <= save_all <= plain, with the
   full-policy ratio >= 2 on the activation-dominated config — the
   pre-compile delta an operator reads before trading HBM for
   recompute.

Plus **decode_parity**: the same paged+chunked+speculative workload
decoded under kernels off vs interpret, tokens sha256-committed equal.

Regenerate: ``python tools/kernel_report.py --out KERNEL_EVIDENCE_r15.json``
Drift gate: tests/test_kernels.py::test_kernel_evidence_r15_committed
re-derives every field live and compares byte-for-byte.
"""

import argparse
import hashlib
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

DECODE_PROMPTS = ([7, 3, 9, 2, 11, 5, 8, 1, 4], [7, 3, 9, 2, 11, 5, 8, 1],
                  [1, 2], [9, 9, 4, 4, 1, 2, 3, 4, 5, 6, 7, 8])


def registry_report():
    from paddle_tpu import kernels

    return {
        "mode_env": kernels.MODE_ENV,
        "modes": ["auto", "off", "interpret"],
        "kernels": [
            {
                "name": s.name, "kind": s.kind, "parity": s.parity,
                "op_types": list(s.op_types), "gated_by": s.gated_by,
                "version": s.version,
            }
            for s in kernels.all_specs()
        ],
        "parity_gate":
            "tests/test_kernels.py::test_kernel_parity[<name>] "
            "(parametrized over kernels.all_specs())",
    }


def amp_flash_report(seq_len=256, max_pred=40):
    """bf16 HLO gates on the flash-kernel train step (interpret
    trace). seq_len 256 keeps [S, S] unambiguous against the kernel's
    own 128x128 block tiles (the test_hlo.py S=512 rationale, cheaper)."""
    from paddle_tpu.utils import hlo

    txt = hlo.bert_train_step_text(
        flash=True, seq_len=seq_len, max_pred=max_pred)
    dots = hlo.stablehlo_dots(txt)
    f32_in = [d for d in dots if not (
        d[0].endswith("bf16") and d[1].endswith("bf16"))]
    tensors = hlo.stablehlo_tensors(txt)
    s2 = hlo.tensors_with_trailing(tensors, (seq_len, seq_len))
    return {
        "seq_len": seq_len,
        "dots_total": len(dots),
        "dots_full_precision": len(f32_in),
        "s2_buffers": sorted(set(s2)),
    }


def paged_hbm_report():
    """Static peak-HBM, kernel-path vs composite-path accounting, at the
    DECODE_EVIDENCE_r13 geometry."""
    from paddle_tpu.analysis.memory import estimate_peak_hbm
    from paddle_tpu.serving.decode import build_decoder_model

    geom = dict(vocab_size=32000, hidden=64, num_layers=16, slots=8,
                max_len=32768)
    S, L, H = geom["slots"], geom["max_len"], geom["hidden"]

    def peak(tag, kernel_path, **kw):
        m = build_decoder_model(name=f"kev_{tag}", version="1", **geom,
                                **kw)
        r = estimate_peak_hbm(
            m.decode_program,
            feed_shapes={n: s for n, s, _d in m.decode_feed_sig()},
            fetch_names=[m.logits_fetch], kernel_path=kernel_path)
        return {
            "peak_total_bytes": r.peak_total_bytes,
            "persistent_bytes": r.persistent_bytes,
            "peak_intermediate_bytes": r.peak_intermediate_bytes,
        }

    slotted = peak("slotted", False, fused_attention=False,
                   block_size=L, num_blocks=S)
    paged_kw = dict(block_size=64, num_blocks=320)
    composite = peak("paged_c", False, **paged_kw)
    kernel = peak("paged_k", True, **paged_kw)
    gather_view_bytes = 2 * S * L * H * 4
    with open(os.path.join(REPO, "DECODE_EVIDENCE_r13.json")) as f:
        r13 = json.load(f)["static_hbm"]["peak_reduction_x"]
    return {
        "config": dict(geom, **paged_kw),
        "slotted_dense": slotted,
        "paged_composite_accounting": composite,
        "paged_kernel_accounting": kernel,
        "dense_gather_view_bytes": gather_view_bytes,
        "gather_view_removed_bytes":
            composite["peak_total_bytes"] - kernel["peak_total_bytes"],
        "composite_reduction_x": round(
            slotted["peak_total_bytes"]
            / float(composite["peak_total_bytes"]), 2),
        "kernel_reduction_x": round(
            slotted["peak_total_bytes"]
            / float(kernel["peak_total_bytes"]), 2),
        "r13_committed_reduction_x": r13,
        "arena_bound_x": round(
            slotted["persistent_bytes"]
            / float(kernel["persistent_bytes"]), 2),
    }


def embedding_admission_report(steps=8):
    """Two-leg deterministic train stream: device admission (zero host
    capacity-slab round-trips) vs the legacy control, host tiers
    bit-identical."""
    import numpy as np

    from paddle_tpu import kernels
    from paddle_tpu.core.scope import Scope
    from paddle_tpu.embedding.store import EmbeddingEngine
    from paddle_tpu.embedding.table import TableConfig
    from paddle_tpu.kernels.embedding import admission_roundtrip_counter

    def drive(mode):
        with kernels.scoped_mode(mode):
            sc = Scope()
            eng = EmbeddingEngine(scope=sc)
            cfg = TableConfig(name="kev_t", dim=4, capacity=24, ep=2,
                              seed=7)
            rt = eng.register(cfg)
            r = np.random.RandomState(0)
            for _step in range(steps):
                ids = r.randint(0, 64, 10).astype(np.int64)
                rt.lookup(ids, dedup=True, train=True)
                slab = np.asarray(sc.find_var(cfg.slab_name))
                sc.set(cfg.slab_name, slab + 0.001)
            rt.flush()
            blocks = rt.store.snapshot_blocks()
            stats = rt.stats()
            eng.close()
            digest = hashlib.sha256()
            for ids, rows in blocks:
                digest.update(ids.tobytes())
                digest.update(rows.tobytes())
            return digest.hexdigest(), stats

    c0 = admission_roundtrip_counter().value
    dev_digest, dev_stats = drive("auto")
    c1 = admission_roundtrip_counter().value
    legacy_digest, _legacy_stats = drive("off")
    c2 = admission_roundtrip_counter().value
    return {
        "steps": steps,
        "device_roundtrips": int(c1 - c0),
        "legacy_roundtrips": int(c2 - c1),
        "bit_identical": dev_digest == legacy_digest,
        "host_tier_sha256": dev_digest,
        "evictions": int(dev_stats["evictions"]),
    }


def remat_report():
    """Static peak-HBM per remat policy on an activation-dominated fc
    stack (pure analysis, no compile)."""
    import paddle_tpu as fluid
    from paddle_tpu.analysis.memory import estimate_peak_hbm, remat_hbm_delta

    def build(policy=None, ckpt=True, layers=8, width=512):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = fluid.data("x", shape=[-1, width], dtype="float32")
            y = fluid.data("y", shape=[-1, 1], dtype="float32")
            h = x
            cps = []
            for i in range(layers):
                h = fluid.layers.fc(h, size=width, act="relu")
                if i % 2 == 1:
                    cps.append(h)
            pred = fluid.layers.fc(h, size=1)
            loss = fluid.layers.mean(
                fluid.layers.square_error_cost(pred, y))
            opt = fluid.optimizer.SGD(learning_rate=0.1)
            if ckpt:
                opt = fluid.optimizer.RecomputeOptimizer(opt,
                                                         policy=policy)
                opt._set_checkpoints(cps[:-1])
            opt.minimize(loss)
        return main

    fs = {"x": (1024, 512), "y": (1024, 1)}
    peaks = {}
    for tag, pol, ck in (("plain", None, False), ("full", "full", True),
                         ("dots", "dots", True),
                         ("save_all", "save_all", True)):
        peaks[tag] = estimate_peak_hbm(
            build(pol, ck), feed_shapes=fs).peak_intermediate_bytes
    delta = remat_hbm_delta(build(None, False), build("full", True),
                            feed_shapes=fs)
    return {
        "config": {"layers": 8, "width": 512, "batch": 1024,
                   "checkpoints_every": 2},
        "peak_intermediate_bytes": peaks,
        "full_policy_saved_bytes": delta["saved_bytes"],
        "full_policy_ratio": round(delta["ratio"], 3),
    }


def decode_parity_report():
    """Kernels off vs interpret over paged + chunked + speculative
    decode, hand-stepped: tokens byte-identical, digest committed."""
    from paddle_tpu import kernels
    from paddle_tpu.serving.decode import GenerationEngine, build_decoder_model

    geom = dict(vocab_size=32, hidden=8, num_layers=2, slots=4, max_len=24)

    def drive(mode):
        with kernels.scoped_mode(mode):
            engine = GenerationEngine(queue_depth=16, breaker_threshold=0)
            entry = engine.register_model(lambda: build_decoder_model(
                block_size=4, chunk_tokens=4, name="kev_dec", version="1",
                **geom))
            engine.register_model(lambda: build_decoder_model(
                block_size=4, name="kev_dec_d", version="1", **geom))
            resps = [engine.submit(list(p), max_new_tokens=5,
                                   model="kev_dec")
                     for p in DECODE_PROMPTS]
            resps.append(engine.submit(
                list(DECODE_PROMPTS[0]), max_new_tokens=5,
                model="kev_dec", draft_model="kev_dec_d", spec_k=2))
            for _ in range(200):
                if all(r.done() for r in resps):
                    break
                entry._iterate()
            outs = [[int(t) for t in r.result(timeout=120)["tokens"]]
                    for r in resps]
            engine.shutdown()
            return outs

    off = drive("off")
    interp = drive("interpret")
    return {
        "prompts": [list(p) for p in DECODE_PROMPTS],
        "modes": ["off", "interpret"],
        "bit_identical": off == interp,
        "tokens_sha256": hashlib.sha256(
            json.dumps(off, sort_keys=True).encode()).hexdigest(),
    }


def build_evidence():
    return {
        "round": 15,
        "registry": registry_report(),
        "amp_flash": amp_flash_report(),
        "paged_hbm": paged_hbm_report(),
        "embedding_admission": embedding_admission_report(),
        "remat": remat_report(),
        "decode_parity": decode_parity_report(),
    }


def check(evidence):
    """The acceptance gates; raises AssertionError with the failing
    claim."""
    amp = evidence["amp_flash"]
    assert amp["dots_total"] > 30, amp
    assert amp["dots_full_precision"] == 0, amp
    assert amp["s2_buffers"] == [], amp
    hbm = evidence["paged_hbm"]
    assert hbm["kernel_reduction_x"] > hbm["r13_committed_reduction_x"], hbm
    assert hbm["gather_view_removed_bytes"] >= \
        0.9 * hbm["dense_gather_view_bytes"], hbm
    emb = evidence["embedding_admission"]
    assert emb["device_roundtrips"] == 0, emb
    assert emb["legacy_roundtrips"] > 0, emb
    assert emb["bit_identical"], emb
    assert emb["evictions"] > 0, emb
    rm = evidence["remat"]
    p = rm["peak_intermediate_bytes"]
    assert p["full"] < p["dots"] <= p["save_all"] <= p["plain"], p
    assert rm["full_policy_ratio"] >= 2.0, rm
    dp = evidence["decode_parity"]
    assert dp["bit_identical"], dp
    names = {k["name"] for k in evidence["registry"]["kernels"]}
    assert {"flash_attention", "cached_attention", "paged_attention",
            "embedding_admission", "remat_policy"} <= names, names


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", default=None,
                    help="write the evidence JSON here")
    args = ap.parse_args(argv)
    evidence = build_evidence()
    check(evidence)
    text = json.dumps(evidence, indent=1, sort_keys=True)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text + "\n")
        print(f"wrote {args.out}")
    else:
        print(text)
    print("KERNEL_EVIDENCE_OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
