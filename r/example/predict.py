"""Python twin of predict.r (reference: r/example/mobilenet.py) — the
executable contract the R script translates through reticulate.

Usage: python predict.py <saved_model_dir> [input.npy]
Builds + saves a tiny conv classifier when the dir is empty, then loads it
through the AnalysisPredictor and prints the output shape/checksum.
"""

import os
import sys

import numpy as np


def ensure_model(model_dir):
    if os.path.exists(os.path.join(model_dir, "__model__")):
        return
    import paddle_tpu as fluid

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = fluid.data("img", shape=[-1, 3, 32, 32], dtype="float32")
        c = fluid.layers.conv2d(img, num_filters=8, filter_size=3, act="relu")
        p = fluid.layers.pool2d(c, pool_size=2, pool_stride=2)
        flat = fluid.layers.reshape(p, [0, 8 * 15 * 15])
        out = fluid.layers.fc(flat, size=10, act="softmax")
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        fluid.io.save_inference_model(
            model_dir, ["img"], [out], exe, main_program=main
        )


def main():
    # decide the backend with the stall watchdog (falls back to CPU when
    # the TPU tunnel hangs) BEFORE any jax computation — same discipline
    # as bench.py
    from paddle_tpu.core.places import ensure_backend_or_cpu

    ensure_backend_or_cpu()
    model_dir = sys.argv[1] if len(sys.argv) > 1 else "/tmp/r_demo_model"
    ensure_model(model_dir)

    from paddle_tpu import inference as paddle_infer

    config = paddle_infer.Config(model_dir)
    config.disable_gpu()
    predictor = paddle_infer.create_predictor(config)

    if len(sys.argv) > 2:
        data = np.load(sys.argv[2]).astype("float32")
    else:
        data = np.random.RandomState(0).randn(1, 3, 32, 32).astype("float32")
    inp = predictor.get_input_handle(predictor.get_input_names()[0])
    inp.copy_from_cpu(data)
    predictor.run()
    out = predictor.get_output_handle(
        predictor.get_output_names()[0]
    ).copy_to_cpu()
    print("output shape:", out.shape, "sum:", float(out.sum()))
    return out


if __name__ == "__main__":
    main()
