#!/usr/bin/env Rscript
# R inference through reticulate (reference: r/example/mobilenet.r — the
# reference's R binding is exactly this pattern: import the Python API).
# predict.py is the executable contract; this file tracks it line for line.

library(reticulate)

# point reticulate at a Python that can `import paddle_tpu`
# use_python("/opt/venv/bin/python")

np <- import("numpy")
paddle_infer <- import("paddle_tpu.inference")

model_dir <- "/tmp/r_demo_model"

set_config <- function() {
    config <- paddle_infer$Config(model_dir)
    config$disable_gpu()
    return(config)
}

run_predict <- function() {
    config <- set_config()
    predictor <- paddle_infer$create_predictor(config)

    input_names <- predictor$get_input_names()
    input_handle <- predictor$get_input_handle(input_names[[1]])
    data <- np$random$RandomState(0L)$randn(1L, 3L, 32L, 32L)
    input_handle$copy_from_cpu(np$float32(data))

    predictor$run()

    output_names <- predictor$get_output_names()
    output_handle <- predictor$get_output_handle(output_names[[1]])
    output_data <- output_handle$copy_to_cpu()
    print(dim(output_data))
    print(sum(output_data))
}

if (!interactive()) {
    run_predict()
}
