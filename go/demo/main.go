// Demo/smoke host for the Go binding: load a model dir, run one batch.
// usage: go run main.go <model_dir>
package main

import (
	"fmt"
	"os"

	"paddle_tpu/go/paddle"
)

func main() {
	cfg := paddle.NewConfig()
	cfg.SetModel(os.Args[1], "")
	cfg.DisableTPU()
	cfg.SwitchIrOptim(true)
	pred, err := paddle.NewPredictor(cfg)
	if err != nil {
		panic(err)
	}
	in := &paddle.Tensor{Shape: []int64{2, 6}, Data: make([]float32, 12)}
	for i := range in.Data {
		in.Data[i] = float32(i) * 0.1
	}
	if err := pred.SetInput(pred.InputNames()[0], in); err != nil {
		panic(err)
	}
	if err := pred.Run(); err != nil {
		panic(err)
	}
	out, err := pred.GetOutput(pred.OutputNames()[0])
	if err != nil {
		panic(err)
	}
	fmt.Println("ok", out.Shape, out.Data)
}
