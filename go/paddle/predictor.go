// Package paddle: Go binding for the paddle_tpu inference C ABI.
//
// reference: go/paddle/predictor.go in the reference repo — the same
// train-in-Python / serve-from-Go workflow, re-based on the TPU-native
// predictor (the C library embeds CPython driving AOT-compiled XLA
// executables; see csrc/capi/paddle_tpu_capi.h).
//
// Build: point cgo at csrc/capi, e.g.
//
//	CGO_CFLAGS="-I/path/to/repo/csrc/capi" \
//	CGO_LDFLAGS="-L/path/to/repo/csrc/capi -lcapi -Wl,-rpath,/path/to/repo/csrc/capi" \
//	go build ./...
package paddle

// #include <stdlib.h>
// #include <paddle_tpu_capi.h>
import "C"

import (
	"fmt"
	"runtime"
	"unsafe"
)

// DataType mirrors PD_DataType.
type DataType int

const (
	Float32 DataType = iota
	Int32
	Int64
	Uint8
)

// Config mirrors AnalysisConfig (reference: go/paddle/config.go).
type Config struct {
	c *C.PD_AnalysisConfig
}

func NewConfig() *Config {
	cfg := &Config{c: C.PD_NewAnalysisConfig()}
	runtime.SetFinalizer(cfg, func(c *Config) { C.PD_DeleteAnalysisConfig(c.c) })
	return cfg
}

// SetModel points at a save_inference_model directory (params == "") or an
// explicit (model file, params file) pair.
func (cfg *Config) SetModel(model, params string) {
	cm := C.CString(model)
	defer C.free(unsafe.Pointer(cm))
	if params == "" {
		C.PD_SetModel(cfg.c, cm, nil)
		return
	}
	cp := C.CString(params)
	defer C.free(unsafe.Pointer(cp))
	C.PD_SetModel(cfg.c, cm, cp)
}

func (cfg *Config) EnableTPU(deviceID int) { C.PD_EnableTPU(cfg.c, C.int(deviceID)) }
func (cfg *Config) DisableTPU()            { C.PD_DisableTPU(cfg.c) }
func (cfg *Config) SwitchIrOptim(on bool) {
	v := C.int(0)
	if on {
		v = 1
	}
	C.PD_SwitchIrOptim(cfg.c, v)
}
func (cfg *Config) EnableBf16() { C.PD_EnableBf16(cfg.c) }

// Tensor is a host-side value crossing the binding.
type Tensor struct {
	Shape []int64
	Data  []float32 // Float32-only convenience surface; extend as needed
}

// Predictor mirrors the reference's paddle.Predictor.
type Predictor struct {
	c *C.PD_Predictor
}

func lastError() error {
	return fmt.Errorf("paddle_tpu: %s", C.GoString(C.PD_GetLastError()))
}

func NewPredictor(cfg *Config) (*Predictor, error) {
	p := C.PD_NewPredictor(cfg.c)
	if p == nil {
		return nil, lastError()
	}
	pred := &Predictor{c: p}
	runtime.SetFinalizer(pred, func(p *Predictor) { C.PD_DeletePredictor(p.c) })
	return pred, nil
}

// Clone shares weights and compiled executables (thread-per-predictor).
func (p *Predictor) Clone() (*Predictor, error) {
	c := C.PD_ClonePredictor(p.c)
	if c == nil {
		return nil, lastError()
	}
	twin := &Predictor{c: c}
	runtime.SetFinalizer(twin, func(p *Predictor) { C.PD_DeletePredictor(p.c) })
	return twin, nil
}

func (p *Predictor) InputNames() []string {
	n := int(C.PD_GetInputNum(p.c))
	out := make([]string, n)
	for i := 0; i < n; i++ {
		out[i] = C.GoString(C.PD_GetInputName(p.c, C.int(i)))
	}
	return out
}

func (p *Predictor) OutputNames() []string {
	n := int(C.PD_GetOutputNum(p.c))
	out := make([]string, n)
	for i := 0; i < n; i++ {
		out[i] = C.GoString(C.PD_GetOutputName(p.c, C.int(i)))
	}
	return out
}

func (p *Predictor) SetInput(name string, t *Tensor) error {
	cn := C.CString(name)
	defer C.free(unsafe.Pointer(cn))
	rc := C.PD_SetInput(p.c, cn, C.PD_FLOAT32,
		(*C.int64_t)(unsafe.Pointer(&t.Shape[0])), C.int(len(t.Shape)),
		unsafe.Pointer(&t.Data[0]))
	if rc != 0 {
		return lastError()
	}
	return nil
}

func (p *Predictor) Run() error {
	if C.PD_PredictorRun(p.c) != 0 {
		return lastError()
	}
	return nil
}

func (p *Predictor) GetOutput(name string) (*Tensor, error) {
	cn := C.CString(name)
	defer C.free(unsafe.Pointer(cn))
	var dt C.PD_DataType
	var shape *C.int64_t
	var ndim C.int
	var data unsafe.Pointer
	var nbytes C.size_t
	if C.PD_GetOutput(p.c, cn, &dt, &shape, &ndim, &data, &nbytes) != 0 {
		return nil, lastError()
	}
	defer C.PD_Free(unsafe.Pointer(shape))
	defer C.PD_Free(data)
	if dt != C.PD_FLOAT32 {
		return nil, fmt.Errorf("paddle_tpu: output %q is not float32", name)
	}
	t := &Tensor{
		Shape: make([]int64, int(ndim)),
		Data:  make([]float32, int(nbytes)/4),
	}
	copy(t.Shape, unsafe.Slice((*int64)(unsafe.Pointer(shape)), int(ndim)))
	copy(t.Data, unsafe.Slice((*float32)(data), int(nbytes)/4))
	return t, nil
}
