// Native data-feed: multithreaded MultiSlot file parsing, in-memory record
// store, shuffle, and padded batch assembly, exposed through a C ABI consumed
// via ctypes (paddle_tpu/dataset.py).
//
// TPU-native equivalent of the reference's C++ data ingestion layer
// (reference: paddle/fluid/framework/data_feed.cc MultiSlotDataFeed — text
// format "per slot: <count> v...", data_set.cc DatasetImpl LoadIntoMemory /
// LocalShuffle). Parsing and batch assembly run in native threads so the
// Python training loop never touches per-sample data; variable-length slots
// come out as padded dense arrays + length vectors (the TPU answer to LoD,
// SURVEY §5.7).
//
// Build: g++ -O2 -shared -fPIC -pthread -o libdatafeed.so datafeed.cc

#include <algorithm>
#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <random>
#include <string>
#include <thread>
#include <vector>

namespace {

enum SlotType { kFloat = 0, kInt64 = 1 };

struct SlotDesc {
  std::string name;
  SlotType type;
  int fixed_len;  // >0 dense, -1 variable-length
};

struct SlotRef {
  uint64_t offset;
  uint32_t len;
};

// Per-thread parse output, merged after join.
struct Shard {
  std::vector<std::vector<float>> fpool;
  std::vector<std::vector<int64_t>> ipool;
  std::vector<SlotRef> refs;  // nrecords * nslots
  size_t nrecords = 0;
  std::string error;
};

struct Dataset {
  std::vector<SlotDesc> slots;
  std::vector<std::vector<float>> fpool;    // per slot
  std::vector<std::vector<int64_t>> ipool;  // per slot
  std::vector<SlotRef> refs;                // nrecords * nslots
  size_t nrecords = 0;

  // pass state
  std::vector<uint64_t> order;
  size_t cursor = 0;
  int batch_size = 1;
  bool drop_last = false;
  std::vector<uint64_t> cur_batch;  // record indices
  std::string error;
};

bool parse_line(const char* p, const char* end,
                const std::vector<SlotDesc>& slots, Shard* out) {
  size_t base = out->refs.size();
  out->refs.resize(base + slots.size());
  for (size_t s = 0; s < slots.size(); ++s) {
    char* next = nullptr;
    long cnt = strtol(p, &next, 10);
    if (next == p || cnt < 0) return false;
    p = next;
    SlotRef& r = out->refs[base + s];
    r.len = static_cast<uint32_t>(cnt);
    if (slots[s].type == kFloat) {
      r.offset = out->fpool[s].size();
      for (long i = 0; i < cnt; ++i) {
        float v = strtof(p, &next);
        if (next == p) return false;
        out->fpool[s].push_back(v);
        p = next;
      }
    } else {
      r.offset = out->ipool[s].size();
      for (long i = 0; i < cnt; ++i) {
        long long v = strtoll(p, &next, 10);
        if (next == p) return false;
        out->ipool[s].push_back(static_cast<int64_t>(v));
        p = next;
      }
    }
    if (p > end) return false;
  }
  out->nrecords++;
  return true;
}

void parse_buffer(const char* data, size_t n, const std::vector<SlotDesc>& slots,
                  Shard* shard) {
  const char* p = data;
  const char* end = data + n;
  while (p < end) {
    const char* nl = static_cast<const char*>(memchr(p, '\n', end - p));
    const char* line_end = nl ? nl : end;
    if (line_end > p) {
      if (!parse_line(p, line_end, slots, shard)) {
        shard->error = "malformed MultiSlot line: " +
                       std::string(p, std::min<size_t>(line_end - p, 120));
        return;
      }
    }
    p = line_end + 1;
  }
}

void parse_file(const std::string& path, const std::vector<SlotDesc>& slots,
                Shard* shard) {
  FILE* f = fopen(path.c_str(), "rb");
  if (!f) {
    shard->error = "cannot open " + path;
    return;
  }
  fseek(f, 0, SEEK_END);
  long n = ftell(f);
  fseek(f, 0, SEEK_SET);
  std::string buf(n, '\0');
  if (n > 0 && fread(&buf[0], 1, n, f) != static_cast<size_t>(n)) {
    shard->error = "short read on " + path;
    fclose(f);
    return;
  }
  fclose(f);
  parse_buffer(buf.data(), buf.size(), slots, shard);
}

void merge_shard(Dataset* ds, Shard&& sh) {
  size_t nslots = ds->slots.size();
  std::vector<uint64_t> fbase(nslots), ibase(nslots);
  for (size_t s = 0; s < nslots; ++s) {
    fbase[s] = ds->fpool[s].size();
    ibase[s] = ds->ipool[s].size();
    ds->fpool[s].insert(ds->fpool[s].end(), sh.fpool[s].begin(),
                        sh.fpool[s].end());
    ds->ipool[s].insert(ds->ipool[s].end(), sh.ipool[s].begin(),
                        sh.ipool[s].end());
  }
  size_t base = ds->refs.size();
  ds->refs.resize(base + sh.refs.size());
  for (size_t r = 0; r < sh.nrecords; ++r) {
    for (size_t s = 0; s < nslots; ++s) {
      SlotRef ref = sh.refs[r * nslots + s];
      ref.offset += (ds->slots[s].type == kFloat) ? fbase[s] : ibase[s];
      ds->refs[base + r * nslots + s] = ref;
    }
  }
  ds->nrecords += sh.nrecords;
}

Shard make_shard(size_t nslots) {
  Shard sh;
  sh.fpool.resize(nslots);
  sh.ipool.resize(nslots);
  return sh;
}

}  // namespace

extern "C" {

// slot_spec: comma-separated "name:f|i:len" (len=-1 for variable length)
void* paddle_ds_create(const char* slot_spec) {
  auto* ds = new Dataset();
  std::string spec(slot_spec);
  size_t pos = 0;
  while (pos < spec.size()) {
    size_t comma = spec.find(',', pos);
    if (comma == std::string::npos) comma = spec.size();
    std::string item = spec.substr(pos, comma - pos);
    size_t c1 = item.find(':');
    size_t c2 = item.find(':', c1 + 1);
    if (c1 == std::string::npos || c2 == std::string::npos) {
      delete ds;
      return nullptr;
    }
    SlotDesc d;
    d.name = item.substr(0, c1);
    d.type = item[c1 + 1] == 'f' ? kFloat : kInt64;
    d.fixed_len = atoi(item.c_str() + c2 + 1);
    ds->slots.push_back(d);
    pos = comma + 1;
  }
  ds->fpool.resize(ds->slots.size());
  ds->ipool.resize(ds->slots.size());
  return ds;
}

void paddle_ds_destroy(void* h) { delete static_cast<Dataset*>(h); }

const char* paddle_ds_error(void* h) {
  return static_cast<Dataset*>(h)->error.c_str();
}

// Threaded load: files are split across nthreads native parser threads
// (reference: data_set.cc LoadIntoMemory thread-per-channel).
int paddle_ds_load_files(void* h, const char** paths, int nfiles,
                         int nthreads) {
  auto* ds = static_cast<Dataset*>(h);
  if (nthreads < 1) nthreads = 1;
  if (nthreads > nfiles) nthreads = nfiles > 0 ? nfiles : 1;
  std::vector<Shard> shards;
  shards.reserve(nfiles);
  for (int i = 0; i < nfiles; ++i) shards.push_back(make_shard(ds->slots.size()));
  std::atomic<int> next_file(0);
  std::vector<std::thread> threads;
  for (int t = 0; t < nthreads; ++t) {
    threads.emplace_back([&]() {
      for (int i = next_file.fetch_add(1); i < nfiles;
           i = next_file.fetch_add(1)) {
        parse_file(paths[i], ds->slots, &shards[i]);
      }
    });
  }
  for (auto& t : threads) t.join();
  // validate every shard BEFORE merging any: a partial merge would leave
  // duplicate records behind a failed-then-retried load
  for (int i = 0; i < nfiles; ++i) {
    if (!shards[i].error.empty()) {
      ds->error = shards[i].error;
      return -1;
    }
  }
  for (int i = 0; i < nfiles; ++i) merge_shard(ds, std::move(shards[i]));
  return 0;
}

int paddle_ds_load_buffer(void* h, const char* data, long n) {
  auto* ds = static_cast<Dataset*>(h);
  Shard sh = make_shard(ds->slots.size());
  parse_buffer(data, static_cast<size_t>(n), ds->slots, &sh);
  if (!sh.error.empty()) {
    ds->error = sh.error;
    return -1;
  }
  merge_shard(ds, std::move(sh));
  return 0;
}

long paddle_ds_size(void* h) {
  return static_cast<long>(static_cast<Dataset*>(h)->nrecords);
}

void paddle_ds_shuffle(void* h, unsigned seed) {
  auto* ds = static_cast<Dataset*>(h);
  if (ds->order.size() != ds->nrecords) {
    ds->order.resize(ds->nrecords);
    for (size_t i = 0; i < ds->nrecords; ++i) ds->order[i] = i;
  }
  std::mt19937_64 gen(seed);
  std::shuffle(ds->order.begin(), ds->order.end(), gen);
}

void paddle_ds_begin_pass(void* h, int batch_size, int drop_last) {
  auto* ds = static_cast<Dataset*>(h);
  if (ds->order.size() != ds->nrecords) {
    ds->order.resize(ds->nrecords);
    for (size_t i = 0; i < ds->nrecords; ++i) ds->order[i] = i;
  }
  ds->cursor = 0;
  ds->batch_size = batch_size;
  ds->drop_last = drop_last != 0;
}

// Advance to the next batch; returns its size (0 = end of pass).
int paddle_ds_next_batch(void* h) {
  auto* ds = static_cast<Dataset*>(h);
  size_t remaining = ds->nrecords - ds->cursor;
  size_t take = std::min<size_t>(ds->batch_size, remaining);
  if (take == 0 || (ds->drop_last && take < static_cast<size_t>(ds->batch_size)))
    return 0;
  ds->cur_batch.assign(ds->order.begin() + ds->cursor,
                       ds->order.begin() + ds->cursor + take);
  ds->cursor += take;
  return static_cast<int>(take);
}

// Max slot length within the current batch (== fixed_len for dense slots).
int paddle_ds_batch_maxlen(void* h, int slot) {
  auto* ds = static_cast<Dataset*>(h);
  size_t nslots = ds->slots.size();
  uint32_t m = 0;
  for (uint64_t r : ds->cur_batch)
    m = std::max(m, ds->refs[r * nslots + slot].len);
  return static_cast<int>(m);
}

// Copy the current batch's slot into out (padded [B, maxlen] row-major) and
// per-row lengths into lens. Returns maxlen. out must hold B*maxlen
// elements of the slot dtype; lens must hold B int64s (may be null).
int paddle_ds_batch_copy(void* h, int slot, void* out, int64_t* lens,
                         int maxlen) {
  auto* ds = static_cast<Dataset*>(h);
  size_t nslots = ds->slots.size();
  const SlotDesc& d = ds->slots[slot];
  for (size_t i = 0; i < ds->cur_batch.size(); ++i) {
    const SlotRef& ref = ds->refs[ds->cur_batch[i] * nslots + slot];
    uint32_t n = std::min<uint32_t>(ref.len, maxlen);
    if (lens) lens[i] = ref.len;
    if (d.type == kFloat) {
      float* row = static_cast<float*>(out) + i * static_cast<size_t>(maxlen);
      memcpy(row, ds->fpool[slot].data() + ref.offset, n * sizeof(float));
      for (uint32_t j = n; j < static_cast<uint32_t>(maxlen); ++j) row[j] = 0.f;
    } else {
      int64_t* row =
          static_cast<int64_t*>(out) + i * static_cast<size_t>(maxlen);
      memcpy(row, ds->ipool[slot].data() + ref.offset, n * sizeof(int64_t));
      for (uint32_t j = n; j < static_cast<uint32_t>(maxlen); ++j) row[j] = 0;
    }
  }
  return maxlen;
}

}  // extern "C"
