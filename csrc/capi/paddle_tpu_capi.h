/* C ABI for the paddle_tpu inference predictor.
 *
 * reference: paddle/fluid/inference/capi/paddle_c_api.h — same role
 * (serve a saved inference model from C/Go hosts), re-based on the
 * TPU-native predictor: the library embeds CPython, which drives the
 * AOT-compiled XLA executables. Thread-safe: every call takes the GIL.
 *
 * Lifetime: buffers returned via PD_GetOutput are malloc'd; release them
 * with PD_Free. All functions returning int use 0 = success, nonzero =
 * failure (then PD_GetLastError() describes it).
 */
#ifndef PADDLE_TPU_CAPI_H_
#define PADDLE_TPU_CAPI_H_

#include <stddef.h>
#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef enum PD_DataType {
  PD_FLOAT32 = 0,
  PD_INT32 = 1,
  PD_INT64 = 2,
  PD_UINT8 = 3,
} PD_DataType;

typedef struct PD_AnalysisConfig PD_AnalysisConfig;
typedef struct PD_Predictor PD_Predictor;

/* -- config (reference: pd_config.cc) ---------------------------------- */
PD_AnalysisConfig* PD_NewAnalysisConfig(void);
void PD_DeleteAnalysisConfig(PD_AnalysisConfig* config);
/* model_dir layout (__model__/__params__): pass params_path = NULL.
 * file layout: pass both paths. */
void PD_SetModel(PD_AnalysisConfig* config, const char* model_path,
                 const char* params_path);
void PD_EnableTPU(PD_AnalysisConfig* config, int device_id);
void PD_DisableTPU(PD_AnalysisConfig* config);
void PD_SwitchIrOptim(PD_AnalysisConfig* config, int enable);
void PD_EnableBf16(PD_AnalysisConfig* config);

/* -- predictor (reference: pd_predictor.cc) ---------------------------- */
PD_Predictor* PD_NewPredictor(const PD_AnalysisConfig* config);
PD_Predictor* PD_ClonePredictor(const PD_Predictor* predictor);
void PD_DeletePredictor(PD_Predictor* predictor);

int PD_GetInputNum(const PD_Predictor* predictor);
int PD_GetOutputNum(const PD_Predictor* predictor);
/* returned name is owned by the predictor; valid until it is deleted */
const char* PD_GetInputName(const PD_Predictor* predictor, int index);
const char* PD_GetOutputName(const PD_Predictor* predictor, int index);

/* copy `data` (dtype/shape as declared) into the named input slot */
int PD_SetInput(PD_Predictor* predictor, const char* name, PD_DataType dtype,
                const int64_t* shape, int ndim, const void* data);
int PD_PredictorRun(PD_Predictor* predictor);
/* fetch the named output: *data is malloc'd (PD_Free), *shape is malloc'd
 * (PD_Free), *ndim / *dtype / *nbytes describe it */
int PD_GetOutput(PD_Predictor* predictor, const char* name,
                 PD_DataType* dtype, int64_t** shape, int* ndim, void** data,
                 size_t* nbytes);

void PD_Free(void* ptr);
const char* PD_GetLastError(void);

/* -- online serving (paddle_tpu/serving: admission queue + dynamic
 * batcher + SLO scheduling over predictor replicas) --------------------
 * Submit/poll surface: PD_ServingSubmit never blocks on inference — it
 * admits (ticket >= 0) or rejects (-1; PD_GetLastError explains, and a
 * full queue asks the caller to back off). Poll from any thread. */
typedef struct PD_ServingEngine PD_ServingEngine;

/* Builds, warms (pre-compiles every shape bucket) and starts the engine.
 * Ladders are power-of-two up to max_batch / max_seq; max_seq 0 = the
 * model has no variable-length axis. queue_depth/max_wait_ms/num_replicas
 * <= 0 pick defaults (256 rows / 5 ms / 1 replica). */
PD_ServingEngine* PD_NewServingEngine(const PD_AnalysisConfig* config,
                                      int max_batch, int max_seq,
                                      int queue_depth, int max_wait_ms,
                                      int num_replicas);
/* graceful drain (queued requests finish), then free */
void PD_DeleteServingEngine(PD_ServingEngine* engine);

/* Submit one request of n_inputs named tensors (parallel arrays; buffers
 * are copied before return). priority: 0 high / 1 normal / 2 low.
 * deadline_ms <= 0 = no deadline. Returns ticket >= 0 or -1. */
int64_t PD_ServingSubmit(PD_ServingEngine* engine, int n_inputs,
                         const char* const* names, const PD_DataType* dtypes,
                         const int64_t* const* shapes, const int* ndims,
                         const void* const* buffers, int priority,
                         int deadline_ms);

/* 0 = served (output buffers filled; free with PD_Free), 1 = pending,
 * 2 = failed (PD_GetLastError). A failed REQUEST consumes the ticket;
 * caller errors (bad ticket, unknown output name) do NOT — release such
 * tickets with PD_ServingRelease. Served tickets stay pollable (other
 * output names) until PD_ServingRelease. */
int PD_ServingPoll(PD_ServingEngine* engine, int64_t ticket,
                   const char* output_name, PD_DataType* dtype,
                   int64_t** shape, int* ndim, void** data, size_t* nbytes);
void PD_ServingRelease(PD_ServingEngine* engine, int64_t ticket);

/* stats snapshot (queue depth, occupancy, p50/p99 latency, rejection and
 * deadline counters, compile-cache hit rate) as a JSON string; PD_Free */
char* PD_ServingStats(PD_ServingEngine* engine);

/* -- train API (reference: paddle/fluid/train/ C++ train demo) ----------
 * model_dir holds main_program/startup_program (+ optional params/) as
 * written by paddle_tpu.io.save_train_model. */
typedef struct PD_Trainer PD_Trainer;

PD_Trainer* PD_NewTrainer(const char* model_dir, int use_tpu);
void PD_DeleteTrainer(PD_Trainer* trainer);
/* "" when the export recorded no loss */
const char* PD_TrainerLossName(const PD_Trainer* trainer);
int PD_TrainerSetInput(PD_Trainer* trainer, const char* name,
                       PD_DataType dtype, const int64_t* shape, int ndim,
                       const void* data);
/* one training step; fetch_name NULL/"" fetches the recorded loss.
 * Output buffers are malloc'd - release with PD_Free. */
int PD_TrainerRunStep(PD_Trainer* trainer, const char* fetch_name,
                      PD_DataType* dtype, int64_t** shape, int* ndim,
                      void** data, size_t* nbytes);
/* save persistables (params + optimizer state) to dirname */
int PD_TrainerSave(PD_Trainer* trainer, const char* dirname);

/* -- ProgramDesc IO (reference: paddle/fluid/framework/c/c_api.cc) ------ */
typedef struct PD_Program PD_Program;

PD_Program* PD_LoadProgram(const char* path);
void PD_DeleteProgram(PD_Program* program);
int PD_SaveProgram(const PD_Program* program, const char* path);
int PD_ProgramOpCount(const PD_Program* program);
/* returned pointer valid until the next PD_ProgramOpType call */
const char* PD_ProgramOpType(const PD_Program* program, int index);

#ifdef __cplusplus
}
#endif
#endif /* PADDLE_TPU_CAPI_H_ */
