// C ABI for the paddle_tpu inference predictor (see paddle_tpu_capi.h).
//
// reference: paddle/fluid/inference/capi/c_api.cc, pd_predictor.cc — the
// same serve-from-C surface, TPU-native edition: this library embeds
// CPython and drives paddle_tpu.inference.capi_bridge, which owns the
// AOT-compiled XLA executables. Only primitive types cross the C↔Python
// boundary (strings, ints, memoryviews, bytes).
//
// Threading: Py_Initialize happens once; afterwards the GIL is released and
// every API call brackets itself with PyGILState_Ensure/Release, so the C
// API is safe to call from any host thread (including Go runtime threads).

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <dlfcn.h>
#include <libgen.h>
#include <cstdlib>
#include <cstring>
#include <mutex>
#include <string>
#include <vector>

#include "paddle_tpu_capi.h"

namespace {

thread_local std::string g_last_error;

void set_error_from_python() {
  PyObject *type = nullptr, *value = nullptr, *tb = nullptr;
  PyErr_Fetch(&type, &value, &tb);
  PyErr_NormalizeException(&type, &value, &tb);
  g_last_error = "unknown python error";
  if (value) {
    PyObject* s = PyObject_Str(value);
    if (s) {
      const char* c = PyUnicode_AsUTF8(s);
      if (c) g_last_error = c;
      Py_DECREF(s);
    }
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
}

// repo root derived from this library's own path (csrc/capi/libcapi.so →
// two directories up), so the embedded interpreter can import paddle_tpu
// without the host process knowing where it lives
std::string repo_root() {
  Dl_info info;
  if (dladdr(reinterpret_cast<void*>(&PD_GetLastError), &info) &&
      info.dli_fname) {
    std::string p(info.dli_fname);
    for (int i = 0; i < 3; ++i) {
      auto pos = p.find_last_of('/');
      if (pos == std::string::npos) break;
      p.erase(pos);
    }
    return p;
  }
  return ".";
}

PyObject* g_bridge = nullptr;  // paddle_tpu.inference.capi_bridge

bool ensure_python() {
  static std::once_flag once;
  static bool ok = false;
  std::call_once(once, [] {
    bool initialized_here = false;
    if (!Py_IsInitialized()) {
      Py_InitializeEx(0);
      initialized_here = true;
    }
    PyGILState_STATE g = PyGILState_Ensure();
    // prepend the repo root so `import paddle_tpu` resolves
    PyObject* sys_path = PySys_GetObject("path");  // borrowed
    PyObject* root = PyUnicode_FromString(repo_root().c_str());
    if (sys_path && root) PyList_Insert(sys_path, 0, root);
    Py_XDECREF(root);
    g_bridge = PyImport_ImportModule("paddle_tpu.inference.capi_bridge");
    if (!g_bridge) {
      set_error_from_python();
    } else {
      ok = true;
    }
    PyGILState_Release(g);
    // When THIS library booted the interpreter, the boot thread still holds
    // the GIL from Py_InitializeEx: drop it for the process lifetime so API
    // calls (from any host thread) can re-take it. When loaded into an
    // existing interpreter (e.g. ctypes), the host owns GIL discipline.
    if (ok && initialized_here) PyEval_SaveThread();
  });
  return ok;
}

// call bridge.<fn>(args...); returns new reference or nullptr (error set)
PyObject* bridge_call(const char* fn, PyObject* args) {
  PyObject* f = PyObject_GetAttrString(g_bridge, fn);
  if (!f) {
    set_error_from_python();
    Py_XDECREF(args);
    return nullptr;
  }
  PyObject* out = PyObject_CallObject(f, args);
  Py_DECREF(f);
  Py_XDECREF(args);
  if (!out) set_error_from_python();
  return out;
}

struct GIL {
  PyGILState_STATE state;
  GIL() : state(PyGILState_Ensure()) {}
  ~GIL() { PyGILState_Release(state); }
};

const size_t kDtypeItemSize[] = {4, 4, 8, 1};  // PD_DataType enum order

// shared marshalling: bridge_fn(obj, name, dtype, shape, memoryview)
int set_named_input(PyObject* obj, const char* bridge_fn, const char* name,
                    int dtype, const int64_t* shape, int ndim,
                    const void* data) {
  if (dtype < 0 || static_cast<size_t>(dtype) >=
                       sizeof(kDtypeItemSize) / sizeof(*kDtypeItemSize)) {
    g_last_error = std::string(bridge_fn) + ": invalid PD_DataType";
    return 1;
  }
  GIL gil;
  size_t n = 1;
  PyObject* shp = PyTuple_New(ndim);
  for (int i = 0; i < ndim; ++i) {
    n *= static_cast<size_t>(shape[i]);
    PyTuple_SetItem(shp, i, PyLong_FromLongLong(shape[i]));
  }
  PyObject* mv = PyMemoryView_FromMemory(
      const_cast<char*>(static_cast<const char*>(data)),
      static_cast<Py_ssize_t>(n * kDtypeItemSize[dtype]), PyBUF_READ);
  PyObject* out = bridge_call(
      bridge_fn, Py_BuildValue("(OsiNN)", obj, name, dtype, shp, mv));
  if (!out) return 1;
  Py_DECREF(out);
  return 0;
}

// shared unpacking of a bridge (dtype, shape, bytes) tuple into malloc'd
// C buffers
int unpack_tensor_tuple(PyObject* out, PD_DataType* dtype, int64_t** shape,
                        int* ndim, void** data, size_t* nbytes) {
  int dt = 0;
  PyObject *shp = nullptr, *raw = nullptr;
  if (!PyArg_ParseTuple(out, "iOO", &dt, &shp, &raw)) {
    set_error_from_python();
    Py_DECREF(out);
    return 1;
  }
  *dtype = static_cast<PD_DataType>(dt);
  *ndim = static_cast<int>(PyTuple_Size(shp));
  *shape = static_cast<int64_t*>(malloc(sizeof(int64_t) * (*ndim)));
  for (int i = 0; i < *ndim; ++i) {
    (*shape)[i] = PyLong_AsLongLong(PyTuple_GetItem(shp, i));
  }
  char* buf = nullptr;
  Py_ssize_t len = 0;
  if (PyBytes_AsStringAndSize(raw, &buf, &len) != 0) {
    set_error_from_python();
    free(*shape);
    Py_DECREF(out);
    return 1;
  }
  *data = malloc(static_cast<size_t>(len));
  memcpy(*data, buf, static_cast<size_t>(len));
  *nbytes = static_cast<size_t>(len);
  Py_DECREF(out);
  return 0;
}

}  // namespace

struct PD_AnalysisConfig {
  std::string model_dir;
  std::string prog_file;
  std::string params_file;
  bool use_tpu = true;
  int device_id = 0;
  bool ir_optim = true;
  bool bf16 = false;
};

struct PD_Predictor {
  PyObject* obj = nullptr;           // bridge Predictor
  std::vector<std::string> inputs;   // cached names (stable char*)
  std::vector<std::string> outputs;
};

struct PD_Trainer {
  PyObject* obj = nullptr;  // bridge _Trainer
  std::string loss_name;
};

struct PD_Program {
  PyObject* obj = nullptr;  // bridge Program
  std::string last_op_type;
};

struct PD_ServingEngine {
  PyObject* obj = nullptr;  // bridge _ServingHandle
};

extern "C" {

PD_AnalysisConfig* PD_NewAnalysisConfig(void) { return new PD_AnalysisConfig; }

void PD_DeleteAnalysisConfig(PD_AnalysisConfig* c) { delete c; }

void PD_SetModel(PD_AnalysisConfig* c, const char* model_path,
                 const char* params_path) {
  if (params_path && *params_path) {
    c->prog_file = model_path;
    c->params_file = params_path;
    c->model_dir.clear();
  } else {
    c->model_dir = model_path;
    c->prog_file.clear();
    c->params_file.clear();
  }
}

void PD_EnableTPU(PD_AnalysisConfig* c, int device_id) {
  c->use_tpu = true;
  c->device_id = device_id;
}

void PD_DisableTPU(PD_AnalysisConfig* c) { c->use_tpu = false; }

void PD_SwitchIrOptim(PD_AnalysisConfig* c, int enable) {
  c->ir_optim = enable != 0;
}

void PD_EnableBf16(PD_AnalysisConfig* c) { c->bf16 = true; }

static bool fill_names(PD_Predictor* p) {
  for (int which = 0; which < 2; ++which) {
    PyObject* names = bridge_call(which ? "output_names" : "input_names",
                                  Py_BuildValue("(O)", p->obj));
    if (!names) return false;
    auto& dst = which ? p->outputs : p->inputs;
    Py_ssize_t n = PyList_Size(names);
    for (Py_ssize_t i = 0; i < n; ++i) {
      const char* s = PyUnicode_AsUTF8(PyList_GetItem(names, i));
      dst.push_back(s ? s : "");
    }
    Py_DECREF(names);
  }
  return true;
}

PD_Predictor* PD_NewPredictor(const PD_AnalysisConfig* c) {
  if (!ensure_python()) return nullptr;
  GIL gil;
  PyObject* obj = bridge_call(
      "new_predictor",
      Py_BuildValue("(sssiiii)", c->model_dir.c_str(), c->prog_file.c_str(),
                    c->params_file.c_str(), c->use_tpu ? 1 : 0, c->device_id,
                    c->ir_optim ? 1 : 0, c->bf16 ? 1 : 0));
  if (!obj) return nullptr;
  auto* p = new PD_Predictor;
  p->obj = obj;
  if (!fill_names(p)) {
    Py_DECREF(p->obj);
    delete p;
    return nullptr;
  }
  return p;
}

PD_Predictor* PD_ClonePredictor(const PD_Predictor* src) {
  if (!ensure_python()) return nullptr;
  GIL gil;
  PyObject* obj =
      bridge_call("clone_predictor", Py_BuildValue("(O)", src->obj));
  if (!obj) return nullptr;
  auto* p = new PD_Predictor;
  p->obj = obj;
  p->inputs = src->inputs;
  p->outputs = src->outputs;
  return p;
}

void PD_DeletePredictor(PD_Predictor* p) {
  if (!p) return;
  if (p->obj) {
    GIL gil;
    Py_DECREF(p->obj);
  }
  delete p;
}

int PD_GetInputNum(const PD_Predictor* p) {
  return static_cast<int>(p->inputs.size());
}

int PD_GetOutputNum(const PD_Predictor* p) {
  return static_cast<int>(p->outputs.size());
}

const char* PD_GetInputName(const PD_Predictor* p, int i) {
  if (i < 0 || i >= static_cast<int>(p->inputs.size())) return nullptr;
  return p->inputs[i].c_str();
}

const char* PD_GetOutputName(const PD_Predictor* p, int i) {
  if (i < 0 || i >= static_cast<int>(p->outputs.size())) return nullptr;
  return p->outputs[i].c_str();
}

int PD_SetInput(PD_Predictor* p, const char* name, PD_DataType dtype,
                const int64_t* shape, int ndim, const void* data) {
  return set_named_input(p->obj, "set_input", name, static_cast<int>(dtype),
                         shape, ndim, data);
}

int PD_PredictorRun(PD_Predictor* p) {
  GIL gil;
  PyObject* out = bridge_call("run", Py_BuildValue("(O)", p->obj));
  if (!out) return 1;
  Py_DECREF(out);
  return 0;
}

int PD_GetOutput(PD_Predictor* p, const char* name, PD_DataType* dtype,
                 int64_t** shape, int* ndim, void** data, size_t* nbytes) {
  GIL gil;
  PyObject* out =
      bridge_call("get_output", Py_BuildValue("(Os)", p->obj, name));
  if (!out) return 1;
  return unpack_tensor_tuple(out, dtype, shape, ndim, data, nbytes);
}

void PD_Free(void* ptr) { free(ptr); }

const char* PD_GetLastError(void) { return g_last_error.c_str(); }

/* -- online serving ----------------------------------------------------- */

PD_ServingEngine* PD_NewServingEngine(const PD_AnalysisConfig* c,
                                      int max_batch, int max_seq,
                                      int queue_depth, int max_wait_ms,
                                      int num_replicas) {
  if (!ensure_python()) return nullptr;
  GIL gil;
  PyObject* obj = bridge_call(
      "new_serving_engine",
      Py_BuildValue("(sssiiiiiii)", c->model_dir.c_str(),
                    c->prog_file.c_str(), c->params_file.c_str(),
                    c->use_tpu ? 1 : 0, c->device_id, max_batch, max_seq,
                    queue_depth, max_wait_ms, num_replicas));
  if (!obj) return nullptr;
  auto* e = new PD_ServingEngine;
  e->obj = obj;
  return e;
}

void PD_DeleteServingEngine(PD_ServingEngine* e) {
  if (!e) return;
  if (e->obj) {
    GIL gil;
    PyObject* out =
        bridge_call("serving_shutdown", Py_BuildValue("(O)", e->obj));
    Py_XDECREF(out);
    Py_DECREF(e->obj);
  }
  delete e;
}

int64_t PD_ServingSubmit(PD_ServingEngine* e, int n_inputs,
                         const char* const* names, const PD_DataType* dtypes,
                         const int64_t* const* shapes, const int* ndims,
                         const void* const* buffers, int priority,
                         int deadline_ms) {
  GIL gil;
  PyObject* name_list = PyList_New(n_inputs);
  PyObject* dtype_list = PyList_New(n_inputs);
  PyObject* shape_list = PyList_New(n_inputs);
  PyObject* buf_list = PyList_New(n_inputs);
  for (int i = 0; i < n_inputs; ++i) {
    int dt = static_cast<int>(dtypes[i]);
    if (dt < 0 || static_cast<size_t>(dt) >=
                      sizeof(kDtypeItemSize) / sizeof(*kDtypeItemSize)) {
      g_last_error = "PD_ServingSubmit: invalid PD_DataType";
      Py_DECREF(name_list);
      Py_DECREF(dtype_list);
      Py_DECREF(shape_list);
      Py_DECREF(buf_list);
      return -1;
    }
    size_t n = 1;
    PyObject* shp = PyTuple_New(ndims[i]);
    for (int d = 0; d < ndims[i]; ++d) {
      n *= static_cast<size_t>(shapes[i][d]);
      PyTuple_SetItem(shp, d, PyLong_FromLongLong(shapes[i][d]));
    }
    PyList_SetItem(name_list, i, PyUnicode_FromString(names[i]));
    PyList_SetItem(dtype_list, i, PyLong_FromLong(dt));
    PyList_SetItem(shape_list, i, shp);
    PyList_SetItem(
        buf_list, i,
        PyMemoryView_FromMemory(
            const_cast<char*>(static_cast<const char*>(buffers[i])),
            static_cast<Py_ssize_t>(n * kDtypeItemSize[dt]), PyBUF_READ));
  }
  PyObject* out = bridge_call(
      "serving_submit",
      Py_BuildValue("(ONNNNii)", e->obj, name_list, dtype_list, shape_list,
                    buf_list, priority, deadline_ms));
  if (!out) return -1;  // rejected — PD_GetLastError has code + retry hint
  int64_t ticket = PyLong_AsLongLong(out);
  Py_DECREF(out);
  return ticket;
}

int PD_ServingPoll(PD_ServingEngine* e, int64_t ticket,
                   const char* output_name, PD_DataType* dtype,
                   int64_t** shape, int* ndim, void** data, size_t* nbytes) {
  GIL gil;
  PyObject* out = bridge_call(
      "serving_poll", Py_BuildValue("(OLs)", e->obj, ticket, output_name));
  if (!out) return 2;  // failed (or bad ticket) — PD_GetLastError
  if (out == Py_None) {
    Py_DECREF(out);
    return 1;  // pending
  }
  return unpack_tensor_tuple(out, dtype, shape, ndim, data, nbytes) ? 2 : 0;
}

void PD_ServingRelease(PD_ServingEngine* e, int64_t ticket) {
  GIL gil;
  PyObject* out =
      bridge_call("serving_release", Py_BuildValue("(OL)", e->obj, ticket));
  Py_XDECREF(out);
}

char* PD_ServingStats(PD_ServingEngine* e) {
  GIL gil;
  PyObject* out =
      bridge_call("serving_stats_json", Py_BuildValue("(O)", e->obj));
  if (!out) return nullptr;
  const char* s = PyUnicode_AsUTF8(out);
  char* copy = s ? strdup(s) : nullptr;
  Py_DECREF(out);
  return copy;
}

/* -- train API ---------------------------------------------------------- */

PD_Trainer* PD_NewTrainer(const char* model_dir, int use_tpu) {
  if (!ensure_python()) return nullptr;
  GIL gil;
  PyObject* obj =
      bridge_call("new_trainer", Py_BuildValue("(si)", model_dir, use_tpu));
  if (!obj) return nullptr;
  auto* t = new PD_Trainer;
  t->obj = obj;
  PyObject* ln =
      bridge_call("trainer_loss_name", Py_BuildValue("(O)", obj));
  if (ln) {
    const char* s = PyUnicode_AsUTF8(ln);
    t->loss_name = s ? s : "";
    Py_DECREF(ln);
  }
  return t;
}

void PD_DeleteTrainer(PD_Trainer* t) {
  if (!t) return;
  if (t->obj) {
    GIL gil;
    Py_DECREF(t->obj);
  }
  delete t;
}

const char* PD_TrainerLossName(const PD_Trainer* t) {
  return t->loss_name.c_str();
}

int PD_TrainerSetInput(PD_Trainer* t, const char* name, PD_DataType dtype,
                       const int64_t* shape, int ndim, const void* data) {
  return set_named_input(t->obj, "trainer_set_input", name,
                         static_cast<int>(dtype), shape, ndim, data);
}

int PD_TrainerRunStep(PD_Trainer* t, const char* fetch_name,
                      PD_DataType* dtype, int64_t** shape, int* ndim,
                      void** data, size_t* nbytes) {
  GIL gil;
  PyObject* out = bridge_call(
      "trainer_run",
      Py_BuildValue("(Os)", t->obj, fetch_name ? fetch_name : ""));
  if (!out) return 1;
  return unpack_tensor_tuple(out, dtype, shape, ndim, data, nbytes);
}

int PD_TrainerSave(PD_Trainer* t, const char* dirname) {
  GIL gil;
  PyObject* out =
      bridge_call("trainer_save", Py_BuildValue("(Os)", t->obj, dirname));
  if (!out) return 1;
  Py_DECREF(out);
  return 0;
}

/* -- ProgramDesc IO ----------------------------------------------------- */

PD_Program* PD_LoadProgram(const char* path) {
  if (!ensure_python()) return nullptr;
  GIL gil;
  PyObject* obj = bridge_call("program_load", Py_BuildValue("(s)", path));
  if (!obj) return nullptr;
  auto* p = new PD_Program;
  p->obj = obj;
  return p;
}

void PD_DeleteProgram(PD_Program* p) {
  if (!p) return;
  if (p->obj) {
    GIL gil;
    Py_DECREF(p->obj);
  }
  delete p;
}

int PD_SaveProgram(const PD_Program* p, const char* path) {
  GIL gil;
  PyObject* out =
      bridge_call("program_save", Py_BuildValue("(Os)", p->obj, path));
  if (!out) return 1;
  Py_DECREF(out);
  return 0;
}

int PD_ProgramOpCount(const PD_Program* p) {
  GIL gil;
  PyObject* out =
      bridge_call("program_op_count", Py_BuildValue("(O)", p->obj));
  if (!out) return -1;
  long n = PyLong_AsLong(out);
  Py_DECREF(out);
  return static_cast<int>(n);
}

const char* PD_ProgramOpType(const PD_Program* p, int index) {
  GIL gil;
  PyObject* out =
      bridge_call("program_op_type", Py_BuildValue("(Oi)", p->obj, index));
  if (!out) return nullptr;
  const char* s = PyUnicode_AsUTF8(out);
  const_cast<PD_Program*>(p)->last_op_type = s ? s : "";
  Py_DECREF(out);
  return p->last_op_type.c_str();
}

}  // extern "C"
