// Native parameter server: sharded sparse/dense tables behind a TCP
// protocol, with server-side optimizer application, checkpoint save/load,
// table shrink, worker barrier and heartbeat tracking.
//
// TPU-native replacement for the reference's PS runtime (reference:
// paddle/fluid/operators/distributed/ — RPCServer + request handlers;
// brpc/grpc transports; fleet_wrapper.h pull/push sparse/dense; heartbeat
// monitor heart_beat_monitor.h:54). gRPC/BRPC are replaced by a dependency-
// free length-prefixed TCP protocol (this image has no grpc dev libs); the
// table/optimizer model follows pslib: embeddings live host-side on servers,
// updates are applied where the rows live, and the TPU only ever sees dense
// pulled rows (XLA hates scatter-heavy workloads — SURVEY §7 hard parts).
//
// Protocol (little-endian):
//   request:  u32 body_len | u8 cmd | u32 table_id | payload
//   response: u32 body_len | u8 status | payload
// Commands: 1=CREATE_TABLE 2=PULL_SPARSE 3=PUSH_SPARSE 4=PULL_DENSE
//           5=PUSH_DENSE 6=SAVE 7=LOAD 8=SHRINK 9=BARRIER 10=HEARTBEAT
//           11=STOP 12=STATS
//
// Build: g++ -O2 -std=c++17 -shared -fPIC -pthread -o libps.so ps.cc

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <chrono>
#include <cmath>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <memory>
#include <mutex>
#include <random>
#include <shared_mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

enum Cmd : uint8_t {
  kCreateTable = 1,
  kPullSparse = 2,
  kPushSparse = 3,
  kPullDense = 4,
  kPushDense = 5,
  kSave = 6,
  kLoad = 7,
  kShrink = 8,
  kBarrier = 9,
  kHeartbeat = 10,
  kStop = 11,
  kStats = 12,
};

enum OptType : uint8_t { kSGD = 0, kAdagrad = 1 };

struct SparseRow {
  std::vector<float> w;
  std::vector<float> g2;  // adagrad accumulator
  uint64_t version = 0;   // bumped on each update; used by shrink
};

struct Table {
  uint8_t is_dense = 0;
  uint32_t dim = 0;
  float init_range = 0.01f;
  uint8_t opt = kSGD;
  // sparse
  std::unordered_map<uint64_t, SparseRow> rows;
  // dense
  std::vector<float> dense;
  std::vector<float> dense_g2;
  uint64_t version = 0;
  std::shared_mutex mu;
};

struct Server {
  ~Server() = default;
  int listen_fd = -1;
  int port = 0;
  std::atomic<bool> stopping{false};
  std::vector<std::thread> threads;
  std::thread accept_thread;
  // shared_ptr: a re-created table must not be freed under a concurrent
  // handler still using the old instance
  std::unordered_map<uint32_t, std::shared_ptr<Table>> tables;
  std::shared_mutex tables_mu;
  // open connection fds, so stop() can shutdown() blocked recv()s
  std::mutex conns_mu;
  std::vector<int> conn_fds;
  // barrier
  std::mutex barrier_mu;
  std::condition_variable barrier_cv;
  uint32_t barrier_count = 0;
  uint64_t barrier_generation = 0;
  // heartbeat: worker id -> last seen (steady seconds)
  std::mutex hb_mu;
  std::unordered_map<uint32_t, double> last_seen;
};

double now_sec() {
  return std::chrono::duration<double>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

bool read_full(int fd, void* buf, size_t n) {
  char* p = static_cast<char*>(buf);
  while (n > 0) {
    ssize_t r = ::recv(fd, p, n, 0);
    if (r <= 0) return false;
    p += r;
    n -= r;
  }
  return true;
}

bool write_full(int fd, const void* buf, size_t n) {
  const char* p = static_cast<const char*>(buf);
  while (n > 0) {
    ssize_t r = ::send(fd, p, n, MSG_NOSIGNAL);
    if (r <= 0) return false;
    p += r;
    n -= r;
  }
  return true;
}

bool send_response(int fd, uint8_t status, const std::string& payload) {
  uint32_t len = static_cast<uint32_t>(1 + payload.size());
  std::string out;
  out.resize(4 + 1 + payload.size());
  memcpy(&out[0], &len, 4);
  out[4] = static_cast<char>(status);
  memcpy(&out[5], payload.data(), payload.size());
  return write_full(fd, out.data(), out.size());
}

template <typename T>
T read_pod(const char*& p) {
  T v;
  memcpy(&v, p, sizeof(T));
  p += sizeof(T);
  return v;
}

template <typename T>
void append_pod(std::string* s, T v) {
  s->append(reinterpret_cast<const char*>(&v), sizeof(T));
}

void init_row(SparseRow* row, const Table& t, uint64_t id) {
  // deterministic per-id init: workers pulling the same id on different
  // servers/restarts see the same fresh vector
  std::mt19937 gen(static_cast<uint32_t>(id * 2654435761u ^ 0x9e3779b9u));
  std::uniform_real_distribution<float> dist(-t.init_range, t.init_range);
  row->w.resize(t.dim);
  for (auto& v : row->w) v = dist(gen);
  if (t.opt == kAdagrad) row->g2.assign(t.dim, 0.f);
}

void apply_update(std::vector<float>* w, std::vector<float>* g2,
                  const float* grad, uint32_t dim, float lr, uint8_t opt) {
  if (opt == kAdagrad) {
    for (uint32_t i = 0; i < dim; ++i) {
      (*g2)[i] += grad[i] * grad[i];
      (*w)[i] -= lr * grad[i] / (std::sqrt((*g2)[i]) + 1e-6f);
    }
  } else {
    for (uint32_t i = 0; i < dim; ++i) (*w)[i] -= lr * grad[i];
  }
}

void handle_conn(Server* srv, int fd) {
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  {
    std::lock_guard<std::mutex> lk(srv->conns_mu);
    srv->conn_fds.push_back(fd);
  }
  std::string body;
  while (!srv->stopping.load()) {
    uint32_t len;
    if (!read_full(fd, &len, 4)) break;
    if (len < 5 || len > (1u << 30)) break;
    body.resize(len);
    if (!read_full(fd, &body[0], len)) break;
    const char* p = body.data();
    uint8_t cmd = read_pod<uint8_t>(p);
    uint32_t table_id = read_pod<uint32_t>(p);

    // validate the per-command fixed header BEFORE any read_pod touches it:
    // a frame long enough for cmd+table_id but shorter than the command's
    // fields would otherwise advance p past the body and make later
    // (end - p) remaining-size math underflow to a huge unsigned value
    {
      uint64_t avail = static_cast<uint64_t>(body.data() + body.size() - p);
      uint64_t fixed_need = 0;
      switch (cmd) {
        case kCreateTable: fixed_need = 18; break;  // u8+u32+u64+f32+u8
        case kPullSparse:
        case kShrink: fixed_need = 8; break;
        case kPushSparse:
        case kPushDense: fixed_need = 12; break;  // f32 lr + u64 n
        case kSave:
        case kLoad:
        case kBarrier:
        case kHeartbeat: fixed_need = 4; break;
        default: break;
      }
      if (avail < fixed_need) {
        send_response(fd, 1, "truncated request");
        continue;
      }
    }

    if (cmd == kStop) {
      send_response(fd, 0, "");
      {
        // barrier_mu held across store+notify: otherwise a waiter that just
        // evaluated its predicate can sleep through the notification forever
        std::lock_guard<std::mutex> lk(srv->barrier_mu);
        srv->stopping.store(true);
      }
      srv->barrier_cv.notify_all();
      break;
    }

    if (cmd == kCreateTable) {
      uint8_t is_dense = read_pod<uint8_t>(p);
      uint32_t dim = read_pod<uint32_t>(p);
      uint64_t dense_size = read_pod<uint64_t>(p);
      float init_range = read_pod<float>(p);
      uint8_t opt = read_pod<uint8_t>(p);
      auto t = std::make_shared<Table>();
      t->is_dense = is_dense;
      t->dim = dim;
      t->init_range = init_range;
      t->opt = opt;
      if (is_dense) {
        t->dense.assign(dense_size, 0.f);
        if (opt == kAdagrad) t->dense_g2.assign(dense_size, 0.f);
      }
      {
        std::unique_lock<std::shared_mutex> lk(srv->tables_mu);
        // replace: the shared_ptr keeps the old instance alive for any
        // handler that already grabbed it
        srv->tables[table_id] = std::move(t);
      }
      send_response(fd, 0, "");
      continue;
    }

    std::shared_ptr<Table> t;
    if (cmd != kBarrier && cmd != kHeartbeat && cmd != kStats) {
      std::shared_lock<std::shared_mutex> lk(srv->tables_mu);
      auto it = srv->tables.find(table_id);
      if (it == srv->tables.end()) {
        send_response(fd, 1, "no such table");
        continue;
      }
      t = it->second;
    }

    switch (cmd) {
      case kPullSparse: {
        uint64_t n = read_pod<uint64_t>(p);
        // never trust wire counts: n ids must fit in the remaining body
        if (n > static_cast<uint64_t>(body.data() + body.size() - p) / 8) {
          send_response(fd, 1, "pull_sparse: id count exceeds body");
          break;
        }
        std::string out;
        out.reserve(n * t->dim * 4);
        {
          // serialize under the lock, send AFTER releasing it — a slow
          // client's socket must not stall every other worker's table access
          std::unique_lock<std::shared_mutex> lk(t->mu);  // may insert
          for (uint64_t i = 0; i < n; ++i) {
            uint64_t id = read_pod<uint64_t>(p);
            auto it = t->rows.find(id);
            if (it == t->rows.end()) {
              it = t->rows.emplace(id, SparseRow()).first;
              init_row(&it->second, *t, id);
            }
            out.append(reinterpret_cast<const char*>(it->second.w.data()),
                       t->dim * 4);
          }
        }
        send_response(fd, 0, out);
        break;
      }
      case kPushSparse: {
        float lr = read_pod<float>(p);
        uint64_t n = read_pod<uint64_t>(p);
        // n ids (8B each) + n*dim grads (4B each) must fit in the body;
        // division form avoids u64 overflow for hostile n/dim
        uint64_t remain = static_cast<uint64_t>(body.data() + body.size() - p);
        if (n > remain / 8 ||
            (t->dim && (remain - n * 8) / 4 / t->dim < n)) {
          send_response(fd, 1, "push_sparse: payload exceeds body");
          break;
        }
        const char* ids_p = p;
        const char* grads_p = p + n * 8;
        std::unique_lock<std::shared_mutex> lk(t->mu);
        t->version++;
        for (uint64_t i = 0; i < n; ++i) {
          uint64_t id;
          memcpy(&id, ids_p + i * 8, 8);
          auto it = t->rows.find(id);
          if (it == t->rows.end()) {
            it = t->rows.emplace(id, SparseRow()).first;
            init_row(&it->second, *t, id);
          }
          it->second.version = t->version;
          apply_update(&it->second.w, &it->second.g2,
                       reinterpret_cast<const float*>(grads_p) + i * t->dim,
                       t->dim, lr, t->opt);
        }
        send_response(fd, 0, "");
        break;
      }
      case kPullDense: {
        std::string out;
        {
          std::shared_lock<std::shared_mutex> lk(t->mu);
          out.assign(reinterpret_cast<const char*>(t->dense.data()),
                     t->dense.size() * 4);
        }
        send_response(fd, 0, out);
        break;
      }
      case kPushDense: {
        float lr = read_pod<float>(p);
        uint64_t n = read_pod<uint64_t>(p);
        std::unique_lock<std::shared_mutex> lk(t->mu);
        if (n != t->dense.size() ||
            n * 4 > static_cast<uint64_t>(body.data() + body.size() - p)) {
          send_response(fd, 1, "dense size mismatch");
          break;
        }
        apply_update(&t->dense, &t->dense_g2,
                     reinterpret_cast<const float*>(p),
                     static_cast<uint32_t>(n), lr, t->opt);
        send_response(fd, 0, "");
        break;
      }
      case kSave: {
        uint32_t plen = read_pod<uint32_t>(p);
        if (plen > static_cast<uint64_t>(body.data() + body.size() - p)) {
          send_response(fd, 1, "truncated path");
          break;
        }
        std::string path(p, plen);
        std::shared_lock<std::shared_mutex> lk(t->mu);
        FILE* f = fopen(path.c_str(), "wb");
        if (!f) {
          send_response(fd, 1, "cannot open " + path);
          break;
        }
        uint8_t has_g2 = t->opt == kAdagrad ? 1 : 0;
        fwrite(&t->is_dense, 1, 1, f);
        fwrite(&has_g2, 1, 1, f);
        fwrite(&t->dim, 4, 1, f);
        if (t->is_dense) {
          uint64_t n = t->dense.size();
          fwrite(&n, 8, 1, f);
          fwrite(t->dense.data(), 4, n, f);
          if (has_g2) fwrite(t->dense_g2.data(), 4, n, f);
        } else {
          uint64_t n = t->rows.size();
          fwrite(&n, 8, 1, f);
          for (auto& kv : t->rows) {
            fwrite(&kv.first, 8, 1, f);
            fwrite(kv.second.w.data(), 4, t->dim, f);
            if (has_g2) fwrite(kv.second.g2.data(), 4, t->dim, f);
          }
        }
        fclose(f);
        send_response(fd, 0, "");
        break;
      }
      case kLoad: {
        uint32_t plen = read_pod<uint32_t>(p);
        if (plen > static_cast<uint64_t>(body.data() + body.size() - p)) {
          send_response(fd, 1, "truncated path");
          break;
        }
        std::string path(p, plen);
        std::unique_lock<std::shared_mutex> lk(t->mu);
        FILE* f = fopen(path.c_str(), "rb");
        if (!f) {
          send_response(fd, 1, "cannot open " + path);
          break;
        }
        uint8_t is_dense, has_g2;
        uint32_t dim;
        uint64_t n;
        if (fread(&is_dense, 1, 1, f) != 1 || fread(&has_g2, 1, 1, f) != 1 ||
            fread(&dim, 4, 1, f) != 1 || fread(&n, 8, 1, f) != 1 ||
            is_dense != t->is_dense || dim != t->dim) {
          fclose(f);
          send_response(fd, 1, "checkpoint/table mismatch");
          break;
        }
        bool ok = true;
        if (t->is_dense) {
          t->dense.resize(n);
          ok = fread(t->dense.data(), 4, n, f) == n;
          if (ok && has_g2) {
            t->dense_g2.resize(n);
            ok = fread(t->dense_g2.data(), 4, n, f) == n;
          }
        } else {
          t->rows.clear();
          for (uint64_t i = 0; i < n && ok; ++i) {
            uint64_t id;
            ok = fread(&id, 8, 1, f) == 1;
            if (!ok) break;
            SparseRow row;
            row.w.resize(dim);
            ok = fread(row.w.data(), 4, dim, f) == dim;
            if (ok && has_g2) {
              row.g2.resize(dim);
              ok = fread(row.g2.data(), 4, dim, f) == dim;
            } else if (t->opt == kAdagrad) {
              row.g2.assign(dim, 0.f);
            }
            // loaded rows start at the current generation — a shrink right
            // after restore must NOT wipe the table
            row.version = t->version + 1;
            t->rows.emplace(id, std::move(row));
          }
          if (ok) t->version++;
        }
        fclose(f);
        if (!ok) {
          send_response(fd, 1, "short read");
          break;
        }
        send_response(fd, 0, "");
        break;
      }
      case kShrink: {
        // drop rows untouched for `keep_versions` updates (reference:
        // fleet_wrapper.h:226 ShrinkSparseTable)
        uint64_t keep_versions = read_pod<uint64_t>(p);
        std::unique_lock<std::shared_mutex> lk(t->mu);
        uint64_t floor =
            t->version > keep_versions ? t->version - keep_versions : 0;
        uint64_t dropped = 0;
        for (auto it = t->rows.begin(); it != t->rows.end();) {
          if (it->second.version <= floor) {
            it = t->rows.erase(it);
            dropped++;
          } else {
            ++it;
          }
        }
        std::string out;
        append_pod(&out, dropped);
        send_response(fd, 0, out);
        break;
      }
      case kBarrier: {
        uint32_t n_workers = read_pod<uint32_t>(p);
        std::unique_lock<std::mutex> lk(srv->barrier_mu);
        uint64_t gen = srv->barrier_generation;
        if (++srv->barrier_count >= n_workers) {
          srv->barrier_count = 0;
          srv->barrier_generation++;
          srv->barrier_cv.notify_all();
        } else {
          srv->barrier_cv.wait(lk, [&] {
            return srv->barrier_generation != gen || srv->stopping.load();
          });
        }
        send_response(fd, 0, "");
        break;
      }
      case kHeartbeat: {
        uint32_t worker = read_pod<uint32_t>(p);
        std::lock_guard<std::mutex> lk(srv->hb_mu);
        srv->last_seen[worker] = now_sec();
        std::string out;
        append_pod<uint32_t>(&out, static_cast<uint32_t>(srv->last_seen.size()));
        for (auto& kv : srv->last_seen) {
          append_pod<uint32_t>(&out, kv.first);
          append_pod<float>(&out, static_cast<float>(now_sec() - kv.second));
        }
        send_response(fd, 0, out);
        break;
      }
      case kStats: {
        std::string out;
        std::shared_lock<std::shared_mutex> lk(srv->tables_mu);
        append_pod<uint32_t>(&out, static_cast<uint32_t>(srv->tables.size()));
        for (auto& kv : srv->tables) {
          append_pod<uint32_t>(&out, kv.first);
          std::shared_lock<std::shared_mutex> tl(kv.second->mu);
          uint64_t n = kv.second->is_dense ? kv.second->dense.size()
                                           : kv.second->rows.size();
          append_pod<uint64_t>(&out, n);
        }
        send_response(fd, 0, out);
        break;
      }
      default:
        send_response(fd, 1, "bad command");
        break;
    }
  }
  {
    // deregister BEFORE close: stop() must never shutdown() a recycled fd
    // number belonging to an unrelated file in this process
    std::lock_guard<std::mutex> lk(srv->conns_mu);
    auto& v = srv->conn_fds;
    v.erase(std::remove(v.begin(), v.end(), fd), v.end());
  }
  ::close(fd);
}

void accept_loop(Server* srv) {
  while (!srv->stopping.load()) {
    int fd = ::accept(srv->listen_fd, nullptr, nullptr);
    if (fd < 0) {
      if (srv->stopping.load()) break;
      continue;
    }
    srv->threads.emplace_back(handle_conn, srv, fd);
  }
}

}  // namespace

extern "C" {

// Start a server on `port` (0 = ephemeral). Returns handle, or null.
void* paddle_ps_start(int port) {
  auto* srv = new Server();
  srv->listen_fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (srv->listen_fd < 0) {
    delete srv;
    return nullptr;
  }
  int one = 1;
  setsockopt(srv->listen_fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::bind(srv->listen_fd, reinterpret_cast<sockaddr*>(&addr),
             sizeof(addr)) < 0 ||
      ::listen(srv->listen_fd, 128) < 0) {
    ::close(srv->listen_fd);
    delete srv;
    return nullptr;
  }
  socklen_t alen = sizeof(addr);
  getsockname(srv->listen_fd, reinterpret_cast<sockaddr*>(&addr), &alen);
  srv->port = ntohs(addr.sin_port);
  srv->accept_thread = std::thread(accept_loop, srv);
  return srv;
}

int paddle_ps_port(void* h) { return static_cast<Server*>(h)->port; }

void paddle_ps_stop(void* h) {
  auto* srv = static_cast<Server*>(h);
  {
    std::lock_guard<std::mutex> lk(srv->barrier_mu);
    srv->stopping.store(true);
  }
  srv->barrier_cv.notify_all();
  ::shutdown(srv->listen_fd, SHUT_RDWR);
  ::close(srv->listen_fd);
  {
    // unblock connection threads parked in recv()
    std::lock_guard<std::mutex> lk(srv->conns_mu);
    for (int fd : srv->conn_fds) ::shutdown(fd, SHUT_RDWR);
  }
  if (srv->accept_thread.joinable()) srv->accept_thread.join();
  for (auto& t : srv->threads)
    if (t.joinable()) t.join();
  delete srv;
}

}  // extern "C"
