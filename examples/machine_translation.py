"""Seq2seq translation with beam search — the reference's
machine_translation book example (reference: python/paddle/fluid/tests/
book/test_machine_translation.py), on a copy task: train the Transformer
encoder-decoder, then serve bucketed beam search through the AOT
translator.

Run: python examples/machine_translation.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def build_programs(src_len=12, tgt_len=12):
    """Pure graph construction (no training, no execution): the tiny
    transformer train program. Returns (main, startup, feed_names,
    fetch_vars, cfg) — also the entry point tools/lint_program.py-style
    program linting uses in CI."""
    import paddle_tpu as fluid
    from paddle_tpu.models import transformer as tfm

    cfg = tfm.TransformerConfig.tiny()
    main_prog, startup, feeds, fetches = tfm.build_wmt_train(
        cfg, src_len=src_len, tgt_len=tgt_len,
        optimizer=fluid.optimizer.Adam(2e-3),
    )
    feed_names = [f if isinstance(f, str) else f.name for f in feeds]
    return main_prog, startup, feed_names, fetches, cfg


def main():
    from paddle_tpu.core.places import ensure_backend_or_cpu

    # short probe: examples must not stall minutes when the TPU tunnel is
    # dark (PADDLE_TPU_FORCE_CPU=1 skips the probe entirely)
    on_acc, diag = ensure_backend_or_cpu(timeout=20, retries=1)
    print(f"backend: {'accelerator' if on_acc else 'cpu'} ({diag})")

    import paddle_tpu as fluid
    from paddle_tpu.models import transformer as tfm

    src_len = tgt_len = 12
    main_prog, startup, _, fetches, cfg = build_programs(src_len, tgt_len)
    exe = fluid.Executor(fluid.TPUPlace(0))
    scope = fluid.Scope()
    rng = np.random.RandomState(0)
    with fluid.scope_guard(scope):
        exe.run(startup)
        for step in range(300):
            feed = tfm.synthetic_batch(rng, 32, src_len, tgt_len, cfg)
            (loss,) = exe.run(main_prog, feed=feed, fetch_list=[fetches[0]])
            if step % 100 == 0:
                print(f"step {step}: loss {float(loss[0]):.3f}")
        params = tfm.params_from_scope(cfg)

    translator = tfm.BucketedBeamTranslator(
        cfg, params, beam_size=4, src_buckets=(12, 16)
    ).warmup(8)
    body = rng.randint(3, cfg.vocab_size, (8, 11)).astype("int64")
    toks, scores = translator.translate(body)
    exact = 0
    for i in range(8):
        got = [t for t in toks[i].tolist()
               if t not in (cfg.pad_id, cfg.eos_id)]
        exact += got == body[i].tolist()
    print(f"beam-decode copy accuracy: {exact}/8; "
          f"{translator.tokens_per_sec():.0f} tokens/s")
    assert exact >= 6, "trained model should copy most sequences"


if __name__ == "__main__":
    main()
