"""Wide&Deep CTR over the parameter-server fleet — the reference's
recommender_system book example modernized to its production shape
(reference: python/paddle/fluid/tests/book/test_recommender_system.py +
dist_ctr.py): sparse features live ONLY on the PS; the in-graph remote
lookup pulls/pushes inside the compiled step with prefetch.

Run: python examples/recommender_system.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def build_programs():
    """Pure graph construction (no PS server, no training): the Wide&Deep
    CTR train program in local mode. Returns (main, startup, feed_names,
    fetch_vars) — also the entry point tools/lint_program.py-style program
    linting uses in CI. (main() builds the remote-PS variant instead, which
    needs an initialized fleet.)"""
    from paddle_tpu.models import ctr

    main_prog, startup, feeds, fetches = ctr.build_ctr_train(
        num_slots=4, ids_per_slot=2, deep_dim=8, hidden=(16,),
        sparse_lr=0.2, ps_mode=False, vocab_size=200,
    )
    feed_names = [f if isinstance(f, str) else f.name for f in feeds]
    return main_prog, startup, feed_names, fetches


def main():
    from paddle_tpu.core.places import ensure_backend_or_cpu

    # short probe: examples must not stall minutes when the TPU tunnel is
    # dark (PADDLE_TPU_FORCE_CPU=1 skips the probe entirely)
    on_acc, diag = ensure_backend_or_cpu(timeout=20, retries=1)
    print(f"backend: {'accelerator' if on_acc else 'cpu'} ({diag})")

    import paddle_tpu as fluid
    from paddle_tpu.distributed import lookup as rl
    from paddle_tpu.fleet import parameter_server as psfleet
    from paddle_tpu.fleet.role_maker import UserDefinedRoleMaker
    from paddle_tpu.models import ctr

    fleet = psfleet.fleet
    fleet.init(UserDefinedRoleMaker(current_id=0, worker_num=1))
    main_prog, startup, feeds, fetches = ctr.build_ctr_train(
        num_slots=4, ids_per_slot=2, deep_dim=8, hidden=(16,),
        sparse_lr=0.2, ps_mode="remote",
    )
    srv = fleet.init_server(port=0)
    rng = np.random.RandomState(3)
    try:
        fleet.init_worker(main_prog)
        exe = fluid.Executor(fluid.TPUPlace(0))
        losses = []
        with fluid.scope_guard(fluid.Scope()):
            exe.run(startup)
            # a SMALL id space so ids repeat across batches and the click
            # signal (a hash of slot 0's ids) is actually learnable
            batches = [
                ctr.synthetic_batch(rng, 64, num_slots=4, ids_per_slot=2,
                                    id_space=200)
                for _ in range(10)
            ] * 6
            for i, feed in enumerate(batches):
                if i + 1 < len(batches):
                    rl.prefetch_for_program(main_prog, batches[i + 1])
                (loss,) = exe.run(main_prog, feed=feed,
                                  fetch_list=[fetches[0]])
                losses.append(float(loss[0]))
                if i % 20 == 0:
                    print(f"step {i}: loss {losses[-1]:.4f}")
        stats = fleet._client.table_stats()
        ctx = rl.active_context()
        print(f"server-side rows: {sum(stats.values())}; "
              f"prefetch hits: {ctx.stats['prefetch_hits']}")
        assert sum(stats.values()) > 0
        first = np.mean(losses[:10])
        last = np.mean(losses[-10:])
        print(f"mean loss first 10 steps {first:.4f} -> last 10 {last:.4f}")
        assert last < first - 0.01, "CTR model did not learn"
    finally:
        fleet.stop_worker()
        srv.stop()


if __name__ == "__main__":
    main()
