"""Online serving of a transformer encoder — engine start -> concurrent
submits -> graceful drain.

A single-block masked-attention encoder (embedding -> scaled dot-product
attention -> residual -> FFN head, all per-token) is saved as an
inference model, then served through `paddle_tpu.serving.ServingEngine`:
requests of mixed batch size, sequence length, and priority arrive from
concurrent client threads; the dynamic batcher coalesces them onto a
fixed (batch, seq-len) bucket lattice that was fully AOT-compiled at
startup, so no request ever pays a trace.

The attention mask rides as an explicit input: the batcher zero-fills
padding, a zero mask position contributes exactly 0 to the softmax —
which is why the padded batched outputs below match the single-request
predictor bit-for-bit.

Run: PADDLE_TPU_FORCE_CPU=1 python examples/serve_transformer.py
"""

import os
import sys
import tempfile
import threading

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

VOCAB, D_MODEL, N_CLASSES = 100, 16, 5


def build_programs(main_prog=None, startup_prog=None):
    """Pure graph construction (no training, no execution): one masked
    self-attention block with a per-token classifier head. Returns
    (main, startup, feed_names, fetch_vars) — also the entry point the
    tools/lint_program.py CI linting uses (tests/test_analysis.py)."""
    import paddle_tpu as fluid

    main_prog = main_prog if main_prog is not None else fluid.Program()
    startup_prog = startup_prog if startup_prog is not None else fluid.Program()
    with fluid.program_guard(main_prog, startup_prog):
        ids = fluid.data("ids", shape=[-1, -1], dtype="int64")
        mask = fluid.data("mask", shape=[-1, -1], dtype="float32")
        emb = fluid.layers.embedding(ids, size=(VOCAB, D_MODEL))
        q = fluid.layers.fc(emb, D_MODEL, num_flatten_dims=2)
        k = fluid.layers.fc(emb, D_MODEL, num_flatten_dims=2)
        v = fluid.layers.fc(emb, D_MODEL, num_flatten_dims=2)
        scores = fluid.layers.matmul(
            q, k, transpose_y=True, alpha=1.0 / float(np.sqrt(D_MODEL))
        )
        # [B, S] key mask -> additive bias: 0 where real, -1e9 where
        # padded (exp underflows to exactly 0, so padding cannot leak)
        bias = fluid.layers.unsqueeze(
            fluid.layers.scale(mask, scale=1e9, bias=-1e9), [1]
        )
        att = fluid.layers.softmax(
            fluid.layers.elementwise_add(scores, bias), axis=-1
        )
        ctx = fluid.layers.matmul(att, v)
        h = fluid.layers.elementwise_add(ctx, emb)
        ffn = fluid.layers.fc(h, 4 * D_MODEL, act="relu", num_flatten_dims=2)
        logits = fluid.layers.fc(ffn, N_CLASSES, num_flatten_dims=2)
    return main_prog, startup_prog, ["ids", "mask"], [logits]


def _make_request(rng, max_len):
    rows = int(rng.randint(1, 3))
    ln = int(rng.randint(2, max_len + 1))
    ids = rng.randint(1, VOCAB, (rows, ln)).astype("int64")
    return {"ids": ids, "mask": np.ones((rows, ln), "float32")}


def main():
    from paddle_tpu.core.places import ensure_backend_or_cpu

    on_acc, diag = ensure_backend_or_cpu(timeout=20, retries=1)
    print(f"backend: {'accelerator' if on_acc else 'cpu'} ({diag})")

    import paddle_tpu as fluid
    from paddle_tpu import inference
    from paddle_tpu.serving import (
        BucketLattice,
        Priority,
        RejectedError,
        ServingEngine,
        ServingError,
    )

    main_prog, startup, feed_names, (logits,) = build_programs()
    exe = fluid.Executor(fluid.TPUPlace(0))
    scope = fluid.Scope()
    with tempfile.TemporaryDirectory() as tmp:
        model_dir = os.path.join(tmp, "encoder")
        with fluid.scope_guard(scope):
            exe.run(startup)
            fluid.io.save_inference_model(
                model_dir, feed_names, [logits], exe, main_program=main_prog
            )

        # -- engine start: warm the whole lattice up front ----------------
        config = inference.Config(model_dir)
        if not on_acc:
            config.disable_tpu()
        lattice = BucketLattice(batch_sizes=(1, 2, 4, 8), seq_lens=(4, 8, 16))
        config.set_serving_buckets(lattice.batch_sizes, lattice.seq_lens)
        engine = ServingEngine(config, lattice=lattice, num_replicas=2,
                               queue_depth=128, max_wait_ms=4.0)
        engine.start()
        print(f"warmed {len(engine.predictor._cache)} buckets "
              f"({engine.predictor.cache_stats()['compile_s']:.2f}s compile)")

        # single-request reference path for parity checking
        ref = inference.create_predictor(config)
        out_name = ref.get_output_names()[0]

        # -- concurrent submits: mixed shapes, lengths, priorities --------
        n_clients, per_client = 6, 10
        results, failures = {}, []
        lock = threading.Lock()

        def client(cid):
            rng = np.random.RandomState(cid)
            for i in range(per_client):
                req = _make_request(rng, max_len=16)
                prio = (Priority.HIGH, Priority.NORMAL, Priority.LOW)[i % 3]
                try:
                    out = engine.submit(
                        req, priority=prio, deadline_ms=30_000
                    ).result(timeout=120)
                except ServingError as e:  # structured: code + message
                    with lock:
                        failures.append(e.to_dict())
                    continue
                expect = ref.run([req["ids"], req["mask"]])[0]
                assert np.array_equal(out[out_name], expect), \
                    f"client {cid} request {i}: served != single-request"
                with lock:
                    results[(cid, i)] = out[out_name].shape

        threads = [threading.Thread(target=client, args=(c,))
                   for c in range(n_clients)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()

        # -- graceful drain ----------------------------------------------
        engine.shutdown()
        try:
            engine.submit(_make_request(np.random.RandomState(0), 8))
            raise AssertionError("post-drain submit must be rejected")
        except RejectedError as e:
            print(f"post-drain submit rejected: {e.to_dict()}")

        stats = engine.stats()
        assert not failures, failures
        assert len(results) == n_clients * per_client
        assert stats["cache_misses"] == 0, "a served shape missed the lattice"
        print(f"served {stats['completed']} requests in {stats['batches']} "
              f"batches (avg {stats['avg_batch_rows']:.2f} rows/batch, "
              f"occupancy {stats['avg_batch_occupancy']:.0%}), "
              f"p99 latency {stats['latency_p99_s'] * 1e3:.1f} ms, "
              f"compile-cache hit rate {stats['cache_hit_rate']:.0%}")
        print("serve_transformer: OK")


if __name__ == "__main__":
    main()
