"""MNIST conv net — the reference's recognize_digits book example
(reference: python/paddle/fluid/tests/book/test_recognize_digits.py), on
synthetic digits: conv-pool-conv-pool-fc, Adam, accuracy metric, then the
AnalysisPredictor serving path.

Run: python examples/recognize_digits.py
"""

import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def synthetic_digits(rng, n):
    """Blob-per-class images: learnable without a dataset download."""
    labels = rng.randint(0, 10, n).astype("int64")
    imgs = rng.randn(n, 1, 28, 28).astype("float32") * 0.1
    for i, c in enumerate(labels):
        r, col = divmod(int(c), 4)
        imgs[i, 0, 4 + r * 7:10 + r * 7, 2 + col * 6:8 + col * 6] += 1.5
    return imgs, labels.reshape(-1, 1)


def build_programs(main_prog=None, startup_prog=None):
    """Pure graph construction (no training, no execution): the conv net,
    loss/metric, and Adam step. Returns (main, startup, feed_names,
    fetch_vars=[loss, acc, prediction]) — also the entry point
    tools/lint_program.py-style program linting uses in CI."""
    import paddle_tpu as fluid

    main_prog = main_prog if main_prog is not None else fluid.Program()
    startup_prog = startup_prog if startup_prog is not None else fluid.Program()
    with fluid.program_guard(main_prog, startup_prog):
        img = fluid.data("img", shape=[-1, 1, 28, 28], dtype="float32")
        label = fluid.data("label", shape=[-1, 1], dtype="int64")
        c1 = fluid.layers.conv2d(img, num_filters=8, filter_size=5, act="relu")
        p1 = fluid.layers.pool2d(c1, pool_size=2, pool_stride=2)
        c2 = fluid.layers.conv2d(p1, num_filters=16, filter_size=5, act="relu")
        p2 = fluid.layers.pool2d(c2, pool_size=2, pool_stride=2)
        flat = fluid.layers.reshape(p2, [0, 16 * 4 * 4])
        prediction = fluid.layers.fc(flat, size=10, act="softmax")
        loss = fluid.layers.mean(
            fluid.layers.cross_entropy(prediction, label)
        )
        acc = fluid.layers.accuracy(prediction, label)
        fluid.optimizer.Adam(learning_rate=1e-3).minimize(loss)
    return main_prog, startup_prog, ["img", "label"], [loss, acc, prediction]


def main():
    from paddle_tpu.core.places import ensure_backend_or_cpu

    # short probe: examples must not stall minutes when the TPU tunnel is
    # dark (PADDLE_TPU_FORCE_CPU=1 skips the probe entirely)
    on_acc, diag = ensure_backend_or_cpu(timeout=20, retries=1)
    print(f"backend: {'accelerator' if on_acc else 'cpu'} ({diag})")

    import paddle_tpu as fluid

    _, _, _, (loss, acc, prediction) = build_programs(
        fluid.default_main_program(), fluid.default_startup_program()
    )

    rng = np.random.RandomState(0)
    xs, ys = synthetic_digits(rng, 512)
    exe = fluid.Executor(fluid.TPUPlace(0))
    exe.run(fluid.default_startup_program())
    for epoch in range(6):
        accs = []
        for i in range(0, 512, 64):
            feed = {"img": xs[i:i + 64], "label": ys[i:i + 64]}
            l, a = exe.run(feed=feed, fetch_list=[loss, acc])
            accs.append(float(a[0]))
        print(f"epoch {epoch}: acc {np.mean(accs):.3f}")
    assert np.mean(accs) > 0.9, "did not learn the digit blobs"

    # serve through the AnalysisPredictor (conv+bn/fc fusion passes apply)
    from paddle_tpu import inference as paddle_infer

    save_dir = tempfile.mkdtemp()
    fluid.io.save_inference_model(save_dir, ["img"], [prediction], exe)
    config = paddle_infer.Config(save_dir)
    predictor = paddle_infer.create_predictor(config)
    h = predictor.get_input_handle(predictor.get_input_names()[0])
    h.copy_from_cpu(xs[:16])
    predictor.run()
    out = predictor.get_output_handle(
        predictor.get_output_names()[0]
    ).copy_to_cpu()
    served_acc = float((out.argmax(1) == ys[:16, 0]).mean())
    print(f"predictor serving acc on 16 samples: {served_acc:.2f}")
    assert served_acc > 0.8


if __name__ == "__main__":
    main()
