"""Linear regression — the reference's first book example
(reference: python/paddle/fluid/tests/book/test_fit_a_line.py), on
synthetic housing-shaped data: train with the default-program API, save an
inference model, reload it and predict.

Run: python examples/fit_a_line.py
"""

import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def build_programs(main_prog=None, startup_prog=None):
    """Pure graph construction (no training, no execution): linear model,
    loss, and SGD step. Returns (main, startup, feed_names,
    fetch_vars=[avg_cost, y_predict]) — also the entry point
    tools/lint_program.py-style program linting uses in CI."""
    import paddle_tpu as fluid

    main_prog = main_prog if main_prog is not None else fluid.Program()
    startup_prog = startup_prog if startup_prog is not None else fluid.Program()
    with fluid.program_guard(main_prog, startup_prog):
        x = fluid.data("x", shape=[-1, 13], dtype="float32")
        y = fluid.data("y", shape=[-1, 1], dtype="float32")
        y_predict = fluid.layers.fc(x, size=1, act=None)
        avg_cost = fluid.layers.mean(
            fluid.layers.square_error_cost(y_predict, y)
        )
        fluid.optimizer.SGD(learning_rate=0.01).minimize(avg_cost)
    return main_prog, startup_prog, ["x", "y"], [avg_cost, y_predict]


def main():
    from paddle_tpu.core.places import ensure_backend_or_cpu

    # short probe: examples must not stall minutes when the TPU tunnel is
    # dark (PADDLE_TPU_FORCE_CPU=1 skips the probe entirely)
    on_acc, diag = ensure_backend_or_cpu(timeout=20, retries=1)
    print(f"backend: {'accelerator' if on_acc else 'cpu'} ({diag})")

    import paddle_tpu as fluid

    _, _, _, (avg_cost, y_predict) = build_programs(
        fluid.default_main_program(), fluid.default_startup_program()
    )

    rng = np.random.RandomState(0)
    w_true = rng.randn(13, 1).astype("float32")
    xs = rng.randn(256, 13).astype("float32")
    ys = xs @ w_true + 0.1 * rng.randn(256, 1).astype("float32")

    exe = fluid.Executor(fluid.TPUPlace(0))
    exe.run(fluid.default_startup_program())
    for epoch in range(50):
        for i in range(0, 256, 32):
            feed = {"x": xs[i:i + 32], "y": ys[i:i + 32]}
            (loss,) = exe.run(feed=feed, fetch_list=[avg_cost])
        if epoch % 10 == 0:
            print(f"epoch {epoch}: loss {float(loss[0]):.4f}")
    assert float(loss[0]) < 0.1, "did not converge"

    # save -> reload -> infer (the book flow)
    save_dir = tempfile.mkdtemp()
    fluid.io.save_inference_model(save_dir, ["x"], [y_predict], exe)
    infer_prog, feed_names, fetch_names = fluid.io.load_inference_model(
        save_dir, exe
    )
    probe = rng.randn(4, 13).astype("float32")
    (pred,) = exe.run(infer_prog, feed={feed_names[0]: probe},
                      fetch_list=fetch_names)
    np.testing.assert_allclose(pred, probe @ w_true, atol=0.5)
    print("inference model round-trip OK; predictions track ground truth")


if __name__ == "__main__":
    main()
