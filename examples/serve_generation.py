"""Continuous-batching generation — a cached-attention decoder served
end-to-end through the iteration-level engine.

A small residual transformer decoder (token+position embedding, per-layer
cached attention + FFN, logits head) is hosted by
`paddle_tpu.serving.decode.GenerationEngine`: mixed-length prompts from
two weighted tenants arrive concurrently, prefill into free KV-arena
slots mid-flight, and step through ONE compiled ``[S, 1]`` decode
executable — finished sequences retire between iterations instead of
holding their slot until the slowest batchmate drains.

Every generation is asserted bit-identical to the offline whole-sequence
reference (full causal re-forward per token), which is the engine's
correctness contract: active-slot masking and the additive ``-1e9``
attention bias make retired slots and stale cache positions contribute
exactly 0.0, so batchmates can never perturb each other.

Run: PADDLE_TPU_FORCE_CPU=1 python examples/serve_generation.py
"""

import os
import sys
import threading

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

VOCAB, HIDDEN, LAYERS, SLOTS, MAX_LEN = 50, 16, 2, 4, 32


def _build_model():
    from paddle_tpu.serving.decode import build_decoder_model

    return build_decoder_model(
        vocab_size=VOCAB, hidden=HIDDEN, num_layers=LAYERS, slots=SLOTS,
        max_len=MAX_LEN, name="storyteller", version="1",
    )


def build_programs():
    """Pure graph construction for the static-analysis CI gates: the
    decoder's prefill program (whole-prompt causal forward at [1, L]) —
    the same weights the decode step reads through the KV arena."""
    from paddle_tpu.serving.decode import DecodeModel

    m = _build_model()
    feed_names = [DecodeModel.PRE_TOKENS, DecodeModel.PRE_POSITIONS,
                  DecodeModel.PRE_BIAS]
    return (m.prefill_program, m.startup_program, feed_names,
            [m.prefill_logits_fetch])


def main():
    from paddle_tpu.core.places import ensure_backend_or_cpu

    on_acc, diag = ensure_backend_or_cpu(timeout=20, retries=1)
    print(f"backend: {'accelerator' if on_acc else 'cpu'} ({diag})")

    from paddle_tpu.serving import Priority
    from paddle_tpu.serving.decode import GenerationEngine
    from paddle_tpu.serving.request import RejectedError

    engine = GenerationEngine(queue_depth=128, hbm_budget_mb=256)
    engine.set_tenant("gold", weight=2.0)
    engine.set_tenant("silver", weight=1.0, max_queued=64)
    entry = engine.register_model(_build_model)
    print(f"hosted {entry.model.label}: {SLOTS} slots x {MAX_LEN} tokens "
          f"({entry.stats()['arena_mib'] * 1024:.0f} KiB KV arena), "
          f"executables from {entry.compile_sources}")
    engine.start()

    # -- concurrent clients: mixed lengths, tenants, priorities ----------
    n_clients, per_client = 4, 6
    results, failures = {}, []
    lock = threading.Lock()

    def client(cid):
        rng = np.random.RandomState(cid)
        tenant = "gold" if cid % 2 == 0 else "silver"
        for i in range(per_client):
            prompt = [int(t) for t in
                      rng.randint(0, VOCAB, size=rng.randint(1, 9))]
            max_new = int(rng.randint(2, 17))
            try:
                out = engine.submit(
                    prompt, max_new_tokens=max_new, tenant=tenant,
                    priority=(Priority.HIGH, Priority.NORMAL,
                              Priority.LOW)[i % 3],
                ).result(timeout=120)
            except Exception as e:
                with lock:
                    failures.append((cid, i, repr(e)))
                continue
            with lock:
                results[(cid, i)] = (prompt, max_new,
                                     [int(t) for t in out["tokens"]])

    threads = [threading.Thread(target=client, args=(c,))
               for c in range(n_clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not failures, failures
    assert len(results) == n_clients * per_client

    # -- the contract: continuous == offline, request by request ---------
    for (cid, i), (prompt, max_new, got) in sorted(results.items()):
        ref = entry.offline_decode(prompt, max_new)
        assert got == ref, f"client {cid} request {i}: {got} != {ref}"
    print(f"verified {len(results)} generations bit-identical to the "
          "offline whole-sequence reference")

    # -- shared-prefix dedup: same prompt pays one prefill ---------------
    hits0 = entry.prefix_cache.hits
    story = [7, 3, 7, 1]
    a = engine.submit(story, max_new_tokens=8).result(timeout=120)
    b = engine.submit(story, max_new_tokens=8).result(timeout=120)
    assert [int(t) for t in a["tokens"]] == [int(t) for t in b["tokens"]]
    assert entry.prefix_cache.hits > hits0
    print("shared-prefix dedup: duplicate prompt served from the prefix "
          "cache, bit-identical")

    # -- graceful drain --------------------------------------------------
    engine.shutdown()
    try:
        engine.submit([1, 2], max_new_tokens=2)
        raise AssertionError("post-drain submit must be rejected")
    except RejectedError as e:
        print(f"post-drain submit rejected: {e}")

    st = entry.stats()
    assert st["completed"] == len(results) + 2
    assert st["failed"] == 0
    print(f"served {st['completed']} requests / "
          f"{st['generated_tokens'] + st['prefill_tokens']} tokens in "
          f"{st['decode_steps']} "
          f"decode steps (occupancy {st['occupancy']:.0%}, "
          f"{st['tokens_per_step']:.2f} tok/step), "
          f"p99 latency {st['latency_p99_s'] * 1e3:.1f} ms, "
          f"tenant tokens {st['tenant_tokens']}")
    print("serve_generation: OK")


if __name__ == "__main__":
    main()
