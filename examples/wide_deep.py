"""Wide&Deep CTR over the sharded embedding engine — BASELINE.md
workload 5 (the reference's flagship parameter-server job,
reference: python/paddle/fluid/tests/unittests/dist_ctr.py), TPU-native:

* sparse features ride ``layers.sharded_embedding`` — hash-partitioned
  device hot caches over a host-RAM overflow tier (paddle_tpu/embedding/),
  ids spanning a 2^40 space with no dense table anywhere;
* click-log records (variable-length id lists per slot) are assembled
  into fixed (ids, weights) batches by the ``sparse_batch`` transform on
  the DataLoader's ordered worker pool (paddle_tpu/dataio/sparse.py);
* the engine's per-step dedup gather + hot cache stats print at the end,
  and AutoCheckpoint(extra_state=engine) demonstrates a bit-identical
  save -> restore -> continue through the format-2 shard path.

Run: python examples/wide_deep.py
"""

import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

NUM_SLOTS = 4
IDS_PER_SLOT = 5
DEEP_DIM = 16
ID_SPACE = 2 ** 40
BATCH = 32
STEPS = 100
EP = 2


def build_programs(main_prog=None, startup_prog=None):
    """Wide (linear, zero-init) + deep (embedding -> MLP) -> sigmoid CTR,
    all sparse features on sharded_embedding tables. Returns
    (main, startup, feed_names, [loss, auc_pred])."""
    import paddle_tpu as fluid

    main_prog = main_prog if main_prog is not None else fluid.Program()
    startup_prog = (startup_prog if startup_prog is not None
                    else fluid.Program())
    with fluid.program_guard(main_prog, startup_prog):
        feeds = []
        wide_parts, deep_parts = [], []
        for i in range(NUM_SLOTS):
            ids = fluid.data(f"slot_{i}", shape=[-1, IDS_PER_SLOT],
                             dtype="int64")
            w = fluid.data(f"slot_{i}_w", shape=[-1, IDS_PER_SLOT],
                           dtype="float32")
            feeds += [ids.name, w.name]
            wide_e = fluid.layers.sharded_embedding(
                ids, 1, capacity=4096, ep=EP, name=f"wide_{i}",
                init_range=0.0, lr=0.1, seed=100 + i,
            )
            deep_e = fluid.layers.sharded_embedding(
                ids, DEEP_DIM, capacity=4096, ep=EP, name=f"deep_{i}",
                init_range=0.01, lr=0.1, seed=200 + i,
            )
            # weighted sum-pool over the slot (padding weight 0 -> its
            # repeated-id rows contribute exactly nothing)
            wexp = fluid.layers.reshape(w, [-1, IDS_PER_SLOT, 1])
            wide_parts.append(fluid.layers.reduce_sum(
                fluid.layers.elementwise_mul(wide_e, wexp), dim=1))
            deep_parts.append(fluid.layers.reduce_sum(
                fluid.layers.elementwise_mul(deep_e, wexp), dim=1))
        label = fluid.data("click", shape=[-1, 1], dtype="float32")
        feeds.append("click")

        wide = fluid.layers.sums(wide_parts)                  # [B, 1]
        deep = fluid.layers.concat(deep_parts, axis=1)
        for h in (64, 32):
            deep = fluid.layers.fc(deep, size=h, act="relu")
        logit = wide + fluid.layers.fc(deep, size=1)
        loss = fluid.layers.mean(
            fluid.layers.sigmoid_cross_entropy_with_logits(logit, label)
        )
        pred = fluid.layers.sigmoid(logit)
        # Adam drives the DENSE half; every sharded table trains with its
        # own row-sparse SGD (the deferred rewrite strips Adam off the
        # slabs — an Adam step on untouched cached rows would break the
        # engine's cache-size invariance)
        fluid.optimizer.Adam(learning_rate=1e-3).minimize(loss)
    return main_prog, startup_prog, feeds, [loss, pred]


def click_log(n, seed=0):
    """Synthetic click-log records: zipfian variable-length id lists per
    slot over a 2^40 space; click probability driven by a hash of slot
    0's ids so the model has signal to learn."""
    from paddle_tpu.embedding.table import splitmix64

    rng = np.random.RandomState(seed)
    for _ in range(n):
        rec_slots = {}
        for i in range(NUM_SLOTS):
            n_ids = rng.randint(1, IDS_PER_SLOT + 1)
            ranks = rng.zipf(1.5, size=n_ids).astype(np.uint64)
            ids = (splitmix64(ranks + np.uint64(i * 1000))
                   % np.uint64(ID_SPACE)).astype(np.int64)
            rec_slots[f"slot_{i}"] = ids.tolist()
        # click rate is a pure function of slot 0's FIRST id: hot head
        # ids recur constantly (zipf 1.5), so their wide rows can
        # memorize the rate — exactly the memorization half of Wide&Deep
        sig = rec_slots["slot_0"][0] % 97
        p = (sig / 97.0) * 0.8 + 0.1
        yield {"slots": rec_slots, "click": float(rng.rand() < p)}


def main():
    from paddle_tpu.core.places import ensure_backend_or_cpu

    on_acc, diag = ensure_backend_or_cpu(timeout=20, retries=1)
    print(f"backend: {'accelerator' if on_acc else 'cpu'} ({diag})")

    import paddle_tpu as fluid
    from paddle_tpu.dataio import make_sparse_batch_transform
    from paddle_tpu.embedding import EmbeddingEngine
    from paddle_tpu.incubate.checkpoint import AutoCheckpoint

    main_p, startup, feed_names, (loss, pred) = build_programs(
        fluid.default_main_program(), fluid.default_startup_program()
    )
    exe = fluid.Executor(fluid.TPUPlace(0) if on_acc else fluid.CPUPlace())
    exe.run(startup)

    engine = EmbeddingEngine()
    ckdir = tempfile.mkdtemp(prefix="wide_deep_ck_")
    ck = AutoCheckpoint(exe, main_p, ckdir, save_interval_steps=20,
                        extra_state=engine)

    # click-log -> (ids, weights, label) batches on the ordered pool
    slot_names = [f"slot_{i}" for i in range(NUM_SLOTS)]
    transform = make_sparse_batch_transform(slot_names, IDS_PER_SLOT)
    loader = fluid.reader.DataLoader.from_generator(
        feed_list=feed_names, capacity=8, num_workers=2,
    ).set_sample_generator(
        lambda: click_log(BATCH * STEPS, seed=0), BATCH,
        sample_transform=transform,
    )

    losses = []
    step = 0
    for feed in loader:
        feed = dict(feed)
        engine.prepare_feed(main_p, feed)
        out = exe.run(main_p, feed=feed, fetch_list=[loss])
        losses.append(float(np.asarray(out[0]).reshape(-1)[0]))
        ck.maybe_save(step)
        step += 1
    head = float(np.mean(losses[:10]))
    tail = float(np.mean(losses[-10:]))
    print(f"trained {step} steps: loss {head:.4f} -> {tail:.4f}")
    assert tail < head, "CTR loss did not improve"

    for t, st in sorted(engine.stats().items()):
        print(f"  table {t}: hit_rate={st['hit_rate']:.2f} "
              f"store_rows={st['store_rows']} evictions={st['evictions']}")

    # save -> fresh engine -> resume -> the next step is bit-identical
    ck.save(step - 1, blocking=True)
    probe = dict(next(iter(
        fluid.reader.DataLoader.from_generator(
            feed_list=feed_names, capacity=2,
        ).set_sample_generator(
            lambda: click_log(BATCH, seed=9), BATCH,
            sample_transform=transform,
        )
    )))
    f1 = dict(probe)
    engine.prepare_feed(main_p, f1, train=False)
    before = np.asarray(exe.run(main_p, feed=f1, fetch_list=[pred])[0])

    engine2 = EmbeddingEngine(scope=fluid.global_scope())
    ck2 = AutoCheckpoint(exe, main_p, ckdir, extra_state=engine2)
    resumed_at = ck2.resume()
    f2 = dict(probe)
    engine2.prepare_feed(main_p, f2, train=False)
    after = np.asarray(exe.run(main_p, feed=f2, fetch_list=[pred])[0])
    assert np.array_equal(before, after), "restore was not bit-identical"
    print(f"resumed at step {resumed_at}: restored predictions "
          "bit-identical through the format-2 shard path")
    engine.close()
    engine2.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
