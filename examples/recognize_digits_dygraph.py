"""MNIST in dygraph (imperative) mode with dygraph-to-static capture —
the reference's test_imperative_mnist pattern on the eager tape
(dygraph/base.py), plus the PR-20 parity gate: at every training step the
same forward is captured with ``to_static`` at the CURRENT weights and
the captured loss must be bit-identical to the eager one (the capture
path and the tape path lower through the same op registry, so any drift
is a real lowering divergence, not float noise).

This file deliberately has no static-graph builder entry point: it is
the imperative counterpart of examples/recognize_digits.py and stays out
of the static-program lint gates.

Run: python examples/recognize_digits_dygraph.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def synthetic_digits(rng, n):
    """Blob-per-class images: learnable without a dataset download."""
    labels = rng.randint(0, 10, n).astype("int64")
    imgs = rng.randn(n, 784).astype("float32") * 0.1
    for i, c in enumerate(labels):
        r, col = divmod(int(c), 4)
        block = np.zeros((28, 28), "float32")
        block[4 + r * 7:10 + r * 7, 2 + col * 6:8 + col * 6] = 1.5
        imgs[i] += block.reshape(-1)
    return imgs, labels.reshape(-1, 1)


def build_model():
    from paddle_tpu.dygraph import Linear
    from paddle_tpu.dygraph.container import Sequential

    return Sequential(
        Linear(784, 64, act="relu"),
        Linear(64, 10),
    )


def compute_loss(model, x, y):
    """Softmax cross-entropy mean — runs eagerly on the tape OR records
    into a Program under capture, same code both ways."""
    from paddle_tpu import dygraph

    logits = model(x)
    ce = dygraph.trace_op(
        "softmax_with_cross_entropy",
        {"Logits": [logits], "Label": [y]},
        {},
        out_slots=("Softmax", "Loss"),
    )["Loss"][0]
    return dygraph.trace_op("mean", {"X": [ce]}, {})["Out"][0]


def main(steps=8, batch=32, lr=0.1, seed=0):
    import paddle_tpu as fluid
    from paddle_tpu import dygraph
    from paddle_tpu.dygraph import to_variable
    from paddle_tpu.dygraph.jit import to_static

    rng = np.random.RandomState(seed)
    imgs, labels = synthetic_digits(rng, steps * batch)

    eager_losses = []
    captured_losses = []
    with dygraph.guard(seed=seed):
        model = build_model()
        opt = fluid.optimizer.SGD(learning_rate=lr)
        for step in range(steps):
            xb = imgs[step * batch:(step + 1) * batch]
            yb = labels[step * batch:(step + 1) * batch]

            # capture the SAME forward at the current weights: to_static
            # freezes parameter values into the captured program, so a
            # fresh capture per step tracks training
            captured = to_static(lambda x, y: compute_loss(model, x, y))
            cap_loss = captured(xb, yb)
            captured_losses.append(
                float(np.asarray(cap_loss.numpy()).reshape(-1)[0])
            )

            x = to_variable(xb)
            y = to_variable(yb)
            loss = compute_loss(model, x, y)
            loss.backward()
            opt.minimize(loss, parameter_list=model.parameters())
            model.clear_gradients()
            eager_losses.append(
                float(np.asarray(loss.numpy()).reshape(-1)[0])
            )

    print("eager   :", " ".join(f"{v:.6f}" for v in eager_losses))
    print("captured:", " ".join(f"{v:.6f}" for v in captured_losses))
    mismatches = [
        i for i, (a, b) in enumerate(zip(eager_losses, captured_losses))
        if a != b
    ]
    if mismatches:
        raise SystemExit(
            f"eager/captured loss divergence at steps {mismatches}: "
            f"dygraph-to-static capture no longer matches the tape"
        )
    print(f"eager == to_static capture (bit-identical, {steps} steps); "
          f"loss {eager_losses[0]:.4f} -> {eager_losses[-1]:.4f}")
    return eager_losses, captured_losses


if __name__ == "__main__":
    main()
