"""MoE expert-parallel transformer over the pipeline runtime — the PR-20
demo composing three axes of parallelism on one Program:

  - an 8-layer residual-MLP trunk inside ``PipelinedStack`` running the
    interleaved 1F1B schedule (``schedule="1f1b", interleave=2``) over a
    ``stage`` mesh axis (parallel/pipeline_runtime/),
  - a top-2 gated ``moe_ffn`` head OUTSIDE the stack (expert dispatch is
    a global all_to_all — it cannot live inside the per-stage manual
    region) sharded over the existing ``expert`` axis,
  - the dense off-mesh fallback: without a mesh both the stack and the
    MoE head run sequentially with bit-identical per-microbatch math, so
    this file trains on one CPU device too.

``build_programs()`` is the CI entry point: defining it opts this file
into the lint smoke gates (shapes + sharding + donation on the 8-way dp
mesh) and the static-analysis runtime-agreement tests automatically.

Run: python examples/moe_pipeline.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

NUM_LAYERS = 8
NUM_MICROBATCHES = 4
HIDDEN = 16
SEQ_LEN = 4
NUM_EXPERTS = 4


def build_programs(num_layers=NUM_LAYERS, num_microbatches=NUM_MICROBATCHES,
                   hidden=HIDDEN, seq_len=SEQ_LEN, num_experts=NUM_EXPERTS,
                   schedule="1f1b", interleave=2, lr=0.05):
    """Pure graph construction. Returns (main, startup, feed_names,
    fetch_vars=[loss]). The schedule rides on the pipeline_stack op's
    attrs, so the same Program retraces when flipped gpipe<->1f1b."""
    import paddle_tpu as fluid

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        # concrete batch (2 per microbatch): the static analyzers price
        # the stack body and the MoE head exactly, nothing symbolic
        batch = 2 * num_microbatches
        x = fluid.data("x", shape=[batch, seq_len, hidden])
        y = fluid.data("y", shape=[batch, seq_len, hidden])
        stack = fluid.layers.PipelinedStack(
            num_layers=num_layers,
            num_microbatches=num_microbatches,
            schedule=schedule,
            interleave=interleave,
        )
        with stack.layer():
            h = stack.input(x)
            w = stack.layer_param([hidden, hidden])
            b = stack.layer_param([hidden], is_bias=True)
            hp = fluid.layers.relu(
                fluid.layers.elementwise_add(fluid.layers.matmul(h, w), b)
            )
            # residual keeps 8 stacked layers trainable at lr=0.05
            stack.output(fluid.layers.scale(
                fluid.layers.elementwise_add(h, hp), scale=0.5
            ))
        trunk = stack()
        moe_out, aux = fluid.layers.moe_ffn(
            trunk, num_experts=num_experts, d_ff=2 * hidden,
            expert_axis="expert",
            param_attr=fluid.ParamAttr(
                initializer=fluid.initializer.NormalInitializer(0, 0.1)
            ),
        )
        mse = fluid.layers.mean(
            fluid.layers.square(fluid.layers.elementwise_sub(moe_out, y))
        )
        loss = fluid.layers.elementwise_add(
            mse, fluid.layers.scale(aux, scale=0.01)
        )
        fluid.optimizer.SGD(learning_rate=lr).minimize(loss)
    return main, startup, ["x", "y"], [loss], stack


def _built(schedule, interleave):
    main, startup, feeds, fetches, stack = build_programs(
        schedule=schedule, interleave=interleave
    )
    return main, startup, feeds, fetches[0], stack


def main():
    import jax

    import paddle_tpu as fluid
    from paddle_tpu.parallel.env import make_mesh

    rng = np.random.RandomState(7)
    batch = NUM_MICROBATCHES * 2
    feed = {
        "x": rng.randn(batch, SEQ_LEN, HIDDEN).astype("float32"),
        "y": rng.randn(batch, SEQ_LEN, HIDDEN).astype("float32"),
    }
    exe = fluid.Executor(fluid.CPUPlace())

    def train(prog_for_run, main_prog, startup, loss, steps=6):
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            exe.run(startup)
            return [
                float(np.asarray(
                    exe.run(prog_for_run, feed=feed, fetch_list=[loss])[0]
                ).reshape(()))
                for _ in range(steps)
            ]

    # dense single-device reference (off-mesh fallback)
    main_prog, startup, _f, loss, _stack = _built("1f1b", 2)
    curve = train(main_prog, main_prog, startup, loss)
    print(f"dense fallback loss: {curve[0]:.4f} -> {curve[-1]:.4f}")

    n_dev = jax.device_count()
    if n_dev >= 4:
        for schedule, v in (("gpipe", None), ("1f1b", 2)):
            main_prog, startup, _f, loss, stack = _built(schedule, v)
            mesh = make_mesh((4,), ("stage",))
            prog = fluid.CompiledProgram(main_prog).with_parallel(
                mesh=mesh, loss_name=loss.name,
                param_specs=stack.param_spec_overrides(),
            )
            curve = train(prog, main_prog, startup, loss)
            print(f"{schedule} over 4 stages loss: "
                  f"{curve[0]:.4f} -> {curve[-1]:.4f}")
    if n_dev >= 8:
        # stage x expert: the trunk pipelines, the MoE head dispatches
        # tokens over the expert axis with all_to_all
        main_prog, startup, _f, loss, stack = _built("1f1b", 2)
        mesh = make_mesh((4, 2), ("stage", "expert"))
        prog = fluid.CompiledProgram(main_prog).with_parallel(
            mesh=mesh, loss_name=loss.name,
            param_specs=stack.param_spec_overrides(),
        )
        curve = train(prog, main_prog, startup, loss)
        print(f"1f1b x expert-parallel loss: "
              f"{curve[0]:.4f} -> {curve[-1]:.4f}")
    print("done")


if __name__ == "__main__":
    main()
