"""Headline benchmark: BERT-base pretraining throughput on one chip.

Prints ONE JSON line: {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}.
The reference publishes no in-repo numbers (see BASELINE.md), so vs_baseline
is reported against the BASELINE.json north-star MFU target (value/target).
"""

import json
import os
import sys
import time

import numpy as np


def main():
    import jax

    import paddle_tpu as fluid
    from paddle_tpu.models import bert

    batch = int(sys.argv[1]) if len(sys.argv) > 1 else 256
    seq_len = int(sys.argv[2]) if len(sys.argv) > 2 else 128
    cfg = bert.BertConfig.base()

    # bf16 AMP is the TPU-native default posture (SURVEY §7: AMP row —
    # bf16-first policy; measured +11% tokens/s over f32 on v5e at this
    # config with identical loss). PADDLE_TPU_BENCH_FP32=1 reverts.
    use_amp = not os.environ.get("PADDLE_TPU_BENCH_FP32")
    main_prog, startup, feeds, fetches = bert.build_bert_pretrain(
        cfg, seq_len=seq_len, lr=1e-4, use_amp=use_amp
    )
    exe = fluid.Executor(fluid.TPUPlace(0))
    exe.run(startup)
    rng = np.random.RandomState(0)
    data = bert.synthetic_batch(rng, batch, seq_len, cfg)

    # warmup (compile)
    for _ in range(2):
        exe.run(main_prog, feed=data, fetch_list=[fetches[0]])
    steps = 10
    t0 = time.perf_counter()
    for _ in range(steps):
        out = exe.run(main_prog, feed=data, fetch_list=[fetches[0]])
    dt = time.perf_counter() - t0
    tokens_per_sec = steps * batch * seq_len / dt

    # MFU estimate: ~6 * params * tokens FLOPs for fwd+bwd
    n_params = sum(
        int(np.prod(p.shape)) for p in main_prog.all_parameters()
    )
    flops_per_token = 6 * n_params
    achieved = tokens_per_sec * flops_per_token
    peak = _chip_peak_flops()
    mfu = achieved / peak if peak else 0.0

    print(
        json.dumps(
            {
                "metric": "bert_base_pretrain_tokens_per_sec_per_chip",
                "value": round(tokens_per_sec, 1),
                "unit": "tokens/s",
                "vs_baseline": round(mfu / 0.5, 4),  # vs the >=50% MFU north star
                "extra": {
                    "batch": batch,
                    "seq_len": seq_len,
                    "params": n_params,
                    "mfu_est": round(mfu, 4),
                    "final_loss": float(np.asarray(out[0]).reshape(-1)[0]),
                },
            }
        )
    )


def _chip_peak_flops():
    """Peak bf16 FLOP/s for the local chip (v5e ~= 394 TFLOP/s bf16)."""
    import jax

    dev = jax.devices()[0]
    kind = getattr(dev, "device_kind", "").lower()
    if "v5 lite" in kind or "v5e" in kind:
        return 394e12
    if "v4" in kind:
        return 275e12
    if "v5p" in kind or "v5" in kind:
        return 459e12
    if "v6" in kind:
        return 918e12
    return 0.0


if __name__ == "__main__":
    main()
