"""Headline benchmark: BERT-base pretraining + ResNet-50 throughput, one chip.

Prints ONE JSON line: {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}.
The primary metric is BERT-base pretrain tokens/s; the second BASELINE.md
headline — ResNet-50 imgs/sec/chip — rides in extra.resnet50 (one line keeps
the driver contract). The reference publishes no in-repo numbers (see
BASELINE.md), so vs_baseline is reported against the BASELINE.json
north-star MFU target (value/target).

Backend robustness (round-1 postmortem: BENCH_r01 was rc=1 because the axon
TPU backend failed to initialize, and a bare jax.devices() can hang >10 min
when the chip tunnel stalls): the benchmark body runs in a WATCHDOG
subprocess with a hard timeout. If the accelerator attempt fails or hangs,
the bench re-runs forced to CPU with a reduced config. The JSON line is
always emitted by the orchestrator — on total failure it carries value 0 and
the diagnostic in "extra".
"""

import json
import os
import subprocess
import sys
import time

import numpy as np

_INNER_ENV = "PADDLE_TPU_BENCH_INNER"


def _emit(value, vs_baseline, extra):
    print(
        json.dumps(
            {
                "metric": "bert_base_pretrain_tokens_per_sec_per_chip",
                "value": value,
                "unit": "tokens/s",
                "vs_baseline": vs_baseline,
                "extra": extra,
            }
        )
    )


def _run_inner(force_cpu, timeout):
    env = dict(os.environ)
    env[_INNER_ENV] = "1"
    if force_cpu:
        env["PADDLE_TPU_FORCE_CPU"] = "1"
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__)] + sys.argv[1:],
            env=env,
            capture_output=True,
            text=True,
            timeout=timeout,
        )
    except subprocess.TimeoutExpired:
        return None, f"bench subprocess timed out after {timeout}s"
    for line in reversed(proc.stdout.strip().splitlines()):
        if line.startswith("{"):
            try:
                return json.loads(line), ""
            except json.JSONDecodeError:
                break
    diag = proc.stderr.strip().splitlines()[-3:] or ["no output"]
    return None, " | ".join(diag)


def main():
    if os.environ.get(_INNER_ENV):
        return _bench()
    # Orchestrate: accelerator attempt under a watchdog, then CPU fallback.
    result, diag = _run_inner(force_cpu=False, timeout=900)
    if result is not None:
        print(json.dumps(result))
        return
    tpu_diag = diag
    result, diag = _run_inner(force_cpu=True, timeout=900)
    if result is not None:
        result.setdefault("extra", {})["backend_diag"] = (
            f"accelerator attempt failed ({tpu_diag}); ran on CPU"
        )
        print(json.dumps(result))
        return
    _emit(0.0, 0.0, {"error_tpu": tpu_diag, "error_cpu": diag})
    sys.exit(1)


def _bench():
    from paddle_tpu.core.places import ensure_backend_or_cpu

    on_tpu, diag = ensure_backend_or_cpu()

    import jax  # noqa: F401  (backend decision made above)

    import paddle_tpu as fluid
    from paddle_tpu.models import bert

    batch = int(sys.argv[1]) if len(sys.argv) > 1 else (256 if on_tpu else 8)
    seq_len = int(sys.argv[2]) if len(sys.argv) > 2 else 128
    steps = 30 if on_tpu else 2
    if not on_tpu:
        # CPU fallback must finish inside the watchdog even when the caller
        # passed TPU-sized args: cap batch, keep the metric shape identical
        batch = min(batch, 8)
    cfg = bert.BertConfig.base()
    from paddle_tpu import kernels as _kernels_probe

    if os.environ.get("PADDLE_TPU_BENCH_FLASH", "1") != "0" and \
            _kernels_probe.probe("flash_attention"):
        # flash path: Pallas fused attention fwd+bwd, taken whenever the
        # kernel registry would actually serve it (auto on TPU, or
        # PADDLE_TPU_KERNELS=interpret anywhere). The kernel applies no
        # attention-prob dropout (enforced, models/bert.py), so that knob
        # is 0 here - recorded in extra so the config change is visible.
        cfg.use_flash_attention = True
        cfg.attention_probs_dropout_prob = 0.0
    from paddle_tpu.utils.flags import flags as _flags

    # hardware-RNG dropout bits by default on the chip (same distribution,
    # cheaper stream than threefry); PADDLE_TPU_RNG_IMPL overrides
    _flags.rng_impl = os.environ.get(
        "PADDLE_TPU_RNG_IMPL", "rbg" if on_tpu else "threefry"
    )

    # bf16 AMP is the TPU-native default posture (SURVEY §7: bf16-first
    # policy). PADDLE_TPU_BENCH_FP32=1 reverts to f32 for comparison runs.
    use_amp = not os.environ.get("PADDLE_TPU_BENCH_FP32")
    max_pred = max(1, seq_len * 15 // 100) + 1  # the standard ~15% recipe
    main_prog, startup, feeds, fetches = bert.build_bert_pretrain(
        cfg, seq_len=seq_len, lr=1e-4, use_amp=use_amp,
        max_predictions_per_seq=max_pred,
    )
    exe = fluid.Executor(fluid.TPUPlace(0))
    exe.run(startup)
    rng = np.random.RandomState(0)
    data = bert.synthetic_batch(
        rng, batch, seq_len, cfg, max_predictions_per_seq=max_pred
    )

    # warmup (compile). Sync by VALUE FETCH, not block_until_ready: under the
    # axon tunnel backend block_until_ready returns before chained device
    # work completes (tools/calibrate_timing.py measured an implied 2857
    # TF/s — 7x physical peak — with block_until_ready vs a consistent
    # 162 TF/s with np.asarray), so a value fetch of the scalar loss is the
    # only trustworthy sync. The loss is a scalar: the fetch costs one
    # tunnel RTT (~70 ms), amortized over the whole timed window.
    for _ in range(3):
        out = exe.run(main_prog, feed=data, fetch_list=[fetches[0]],
                      return_numpy=False)
    np.asarray(out[0])  # drain the queue before the timed region
    t0 = time.perf_counter()
    for _ in range(steps):
        # return_numpy=False keeps the loop async: fetches stay on device so
        # step N+1's host-side dispatch overlaps step N's device execution;
        # the final-loss fetch below is the only sync point
        out = exe.run(main_prog, feed=data, fetch_list=[fetches[0]],
                      return_numpy=False)
    final_loss = float(np.asarray(out[0]).reshape(-1)[0])
    dt = time.perf_counter() - t0
    tokens_per_sec = steps * batch * seq_len / dt

    # MFU estimate: ~6 * params * tokens FLOPs for fwd+bwd
    n_params = sum(
        int(np.prod(p.shape)) for p in main_prog.all_parameters()
    )
    flops_per_token = 6 * n_params
    achieved = tokens_per_sec * flops_per_token
    peak = _chip_peak_flops() if on_tpu else 0.0
    mfu = achieved / peak if peak else 0.0
    # measured roofline (VERDICT r3 item 2): a pure-matmul chain timed with
    # the same value-fetch sync gives the rig's ACHIEVABLE TF/s; mfu_est is
    # vs book peak, frac_of_roofline vs this measurement
    roofline = _measure_roofline() if on_tpu else 0.0
    frac_roofline = achieved / roofline if roofline else 0.0


    # flash_attention is a LIVE registry probe (paddle_tpu/kernels/,
    # imported above): would the Pallas flash kernel serve the sdpa op
    # on this backend under the current PADDLE_TPU_KERNELS mode
    # (auto/off/interpret — set PADDLE_TPU_KERNELS=off to opt out of
    # every registry kernel)?
    extra = {
        "device": "tpu" if on_tpu else "cpu",
        "backend_diag": diag,
        "batch": batch,
        "seq_len": seq_len,
        "params": n_params,
        "mfu_est": round(mfu, 4),
        "roofline_tfps": round(roofline / 1e12, 1) if roofline else 0.0,
        "frac_of_roofline": round(frac_roofline, 4),
        "final_loss": final_loss,
        "flash_attention": _kernels_probe.probe("flash_attention"),
        "kernels": {
            "mode": _kernels_probe.mode(),
            "resolved": _kernels_probe.resolved_mode(),
            "registry": [s.name for s in _kernels_probe.all_specs()],
        },
        "max_predictions_per_seq": max_pred,
        "attention_dropout": cfg.attention_probs_dropout_prob,
        "rng_impl": _flags.rng_impl,
        # compile/cache evidence: on the CPU fallback tokens/s is noise,
        # so the cache win shows up here — trace count, hit counts, and
        # whether steps came from the persistent tier
        "compile": _compile_evidence(),
    }
    if not os.environ.get("PADDLE_TPU_BENCH_NO_RESNET"):
        try:
            extra["resnet50"] = _bench_resnet(on_tpu, peak)
        except Exception as e:  # keep the primary metric alive
            extra["resnet50"] = {"error": str(e)[:300]}
    if not os.environ.get("PADDLE_TPU_BENCH_NO_DECODE"):
        try:
            extra["decode"] = _bench_decode()
        except Exception as e:
            extra["decode"] = {"error": str(e)[:300]}
    if not os.environ.get("PADDLE_TPU_BENCH_NO_COST"):
        try:
            extra["cost"] = _bench_cost(main_prog, data, fetches)
        except Exception as e:
            extra["cost"] = {"error": str(e)[:300]}
    if not os.environ.get("PADDLE_TPU_BENCH_NO_PIPELINE"):
        try:
            extra["pipeline"] = _bench_pipeline()
        except Exception as e:
            extra["pipeline"] = {"error": str(e)[:300]}
    _emit(
        round(tokens_per_sec, 1),
        round(mfu / 0.5, 4),  # vs the >=50% MFU north star
        extra,
    )


def _bench_cost(main_prog, data, fetches):
    """Static roofline prediction for the bench program (analysis/cost.py,
    r16): the PRE-COMPILE counterpart of mfu_est — predicted step time,
    MFU, and bound-class counts on the default machine model, so the
    bench records how far the measured number sits from the static
    roofline it will one day be gated against."""
    import numpy as np

    from paddle_tpu.analysis.cost import analyze_cost

    feed_shapes = {k: tuple(np.asarray(v).shape) for k, v in data.items()}
    fetch_names = [f if isinstance(f, str) else f.name for f in fetches]
    rep = analyze_cost(main_prog, feed_shapes=feed_shapes,
                       fetch_names=fetch_names)
    return {
        "machine": rep.cost_model.machine.name,
        "step_seconds": round(rep.step_seconds, 9),
        "mfu_pred": round(rep.mfu, 6),
        "total_flops": rep.total_flops,
        "total_hbm_bytes": rep.total_hbm_bytes,
        "bound_counts": rep.bound_counts(),
        "unknown_ops": sorted(rep.unknown_ops),
    }


def _bench_pipeline():
    """Pipeline-schedule evidence for `extra` (r20): the compiled slot
    tables at the COST_EVIDENCE operating point (4 stages x 4
    microbatches) — predicted vs table-walk realized bubble per schedule.
    Pure schedule-compiler arithmetic, no devices; the realized numbers
    must match PIPELINE_EVIDENCE_r20.json's step accounting."""
    from paddle_tpu.parallel.pipeline_runtime.schedule import (
        compile_schedule,
    )

    out = {"stages": 4, "num_microbatches": 4, "schedules": {}}
    for kind in ("gpipe", "1f1b"):
        sched = compile_schedule(kind, 4, 4)
        out["schedules"][kind] = {
            "interleave": sched.interleave,
            "ticks": sched.num_ticks,
            "predicted_bubble": round(sched.predicted(), 6),
            "realized_bubble": round(sched.realized_bubble(), 6),
            "peak_stash_slots": sched.peak_stash_slots(),
        }
    return out


def _bench_decode():
    """Decode-serving evidence for `extra` (r13): paged block-pool
    occupancy + radix dedup on a share-heavy admission, and speculative
    acceptance/steps-per-token through a byte-identical draft entry.
    Deterministic hand-stepped engines — counters, not wall-clock."""
    from paddle_tpu.serving.decode import GenerationEngine, build_decoder_model

    geom = dict(vocab_size=32, hidden=8, num_layers=2, slots=4, max_len=24)
    engine = GenerationEngine(queue_depth=32, breaker_threshold=0)
    tgt = engine.register_model(lambda: build_decoder_model(
        block_size=4, name="bench_dec", version="1", **geom))
    engine.register_model(lambda: build_decoder_model(
        block_size=4, name="bench_dec_draft", version="1", **geom))
    prefix = [3, 1, 4, 1, 5, 9, 2, 6]
    resps = [engine.submit(prefix + [i], model="bench_dec",
                           max_new_tokens=6) for i in range(3)]
    tgt._admit_free_slots()
    mid = tgt.block_pool.stats()
    for _ in range(geom["max_len"]):
        if all(r.done() for r in resps):
            break
        tgt._step()
    engine.start()
    engine.submit(prefix, model="bench_dec", max_new_tokens=10,
                  draft_model="bench_dec_draft",
                  spec_k=3).result(timeout=300)
    st = tgt.stats()
    engine.shutdown()
    return {
        "block_size": tgt.model.block_size,
        "block_pool_occupancy": round(mid["occupancy"], 3),
        "block_dedup_ratio": round(mid["dedup_ratio"], 3),
        "radix_hits": mid["radix_hits"],
        "arena_mib": round(st["arena_mib"], 4),
        "slotted_equivalent_mib": round(st["slotted_equivalent_mib"], 4),
        "spec_acceptance_rate": round(st["spec_acceptance_rate"], 3),
        "spec_steps_per_token": round(st["spec_steps_per_token"], 3),
    }


def _compile_evidence():
    """Compile-cache counters for `extra`: how many traces the run paid,
    how many steps hit the in-memory cache, and whether executables came
    from the persistent tier (PADDLE_TPU_CACHE_DIR)."""
    from paddle_tpu.core.compile_cache import cache_dir
    from paddle_tpu.observability import metrics as obs_metrics

    reg = obs_metrics.registry()

    def val(name):
        m = reg.get(name)
        return int(m.value) if m is not None else 0

    return {
        "traces": val("executor_cache_misses_total"),
        "cache_hits": val("executor_cache_hits_total"),
        "persistent_hits": val("compile_cache_persistent_hits_total"),
        "memory_tier_hits": val("compile_cache_memory_hits_total"),
        "persistent_cache_dir": cache_dir() or "",
    }


def _bench_resnet(on_tpu, peak):
    """ResNet-50 ImageNet train throughput (BASELINE.md headline 2)."""
    import time

    import jax
    import numpy as np

    import paddle_tpu as fluid
    from paddle_tpu.models import resnet

    batch = 128 if on_tpu else 4
    steps = 20 if on_tpu else 2
    main, startup, feeds, fetches = resnet.build_resnet_train(
        depth=50, class_dim=1000, lr=0.1,
        use_amp=not os.environ.get("PADDLE_TPU_BENCH_FP32"),
    )
    exe = fluid.Executor(fluid.TPUPlace(0))
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        rng = np.random.RandomState(0)
        feed = {
            "img": rng.randn(batch, 3, 224, 224).astype("float32"),
            "label": rng.randint(0, 1000, (batch, 1)).astype("int64"),
        }
        for _ in range(3):
            out = exe.run(main, feed=feed, fetch_list=[fetches[0]],
                          return_numpy=False)
        np.asarray(out[0])  # value-fetch sync (see BERT section)
        t0 = time.perf_counter()
        for _ in range(steps):
            out = exe.run(main, feed=feed, fetch_list=[fetches[0]],
                          return_numpy=False)
        np.asarray(out[0])
        dt = time.perf_counter() - t0
    imgs_per_sec = steps * batch / dt
    # ~7.7 GFLOP fwd per 224x224 image at bs>=1; x3 for fwd+bwd
    flops_per_img = 3 * 7.7e9
    mfu = imgs_per_sec * flops_per_img / peak if peak else 0.0
    return {
        "metric": "resnet50_imgs_per_sec_per_chip",
        "value": round(imgs_per_sec, 2),
        "unit": "imgs/s",
        "batch": batch,
        "mfu_est": round(mfu, 4),
    }


def _measure_roofline(n=4096, inner=50):
    """Achievable bf16 matmul FLOP/s on THIS rig, timed with the same
    value-fetch sync discipline the bench uses (tools/calibrate_timing.py
    stage 3). ~2s on-chip; 0.0 on failure so the bench never dies here."""
    import jax
    import jax.numpy as jnp

    try:
        key = jax.random.PRNGKey(0)
        x = jax.random.normal(key, (n, n), jnp.bfloat16)
        w = jax.random.normal(key, (n, n), jnp.bfloat16)

        @jax.jit
        def pure(z, wz):
            def body(_, y):
                return y @ wz
            return jnp.sum(
                jax.lax.fori_loop(0, inner, body, z).astype(jnp.float32)
            )

        np.asarray(pure(x, w))  # compile + settle
        t0 = time.perf_counter()
        np.asarray(pure(x, w))
        dt = time.perf_counter() - t0
        return 2 * n * n * n * inner / dt
    except Exception:
        return 0.0


def _chip_peak_flops():
    """Peak bf16 FLOP/s for the local chip (v5e ~= 394 TFLOP/s bf16)."""
    import jax

    dev = jax.devices()[0]
    kind = getattr(dev, "device_kind", "").lower()
    if "v5 lite" in kind or "v5e" in kind:
        return 394e12
    if "v4" in kind:
        return 275e12
    if "v5p" in kind or "v5" in kind:
        return 459e12
    if "v6" in kind:
        return 918e12
    return 0.0


if __name__ == "__main__":
    main()
